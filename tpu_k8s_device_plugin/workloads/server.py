"""HTTP front door for the native continuous-batching engine.

The reference's serving example exists to be CALLED — a vLLM Deployment
plus Service with a documented curl smoke test
(/root/reference/example/vllm-serve/service.yaml:1,
/root/reference/README.md:144-156).  This module is the native
counterpart's admission surface: a stdlib HTTP server in front of
``serving.ServingEngine`` that streams tokens per request while the
engine keeps all slots decoding.

Design: ONE scheduler thread owns the engine (admission, decode,
harvest — the engine is not thread-safe and never needs to be); HTTP
handler threads only enqueue requests and drain per-request event
queues.  The loop drives ``scheduler.IterationScheduler`` —
iteration-level continuous batching: decode runs as ``run_scan``
windows (one compiled scan per window, no per-token host round-trip)
whose dispatch/harvest seam the scheduler uses to slide admission work
INSIDE the open window — prefill chunks, new arrivals, and admission
finishes all overlap in-flight decode, a request arriving mid-window
starts prefilling before that window closes, and its first token
streams the moment its splice lands.  Windows grow adaptively
(quantized multiples of ``--window``; see docs §Continuous batching)
when every running request still needs the steps.

API (JSON over HTTP/1.1):

  POST /generate   {"tokens": [int...], "max_new_tokens": N?,
                    "temperature": f?, "top_k": k?, "top_p": p?,
                    "min_p": m?, "presence_penalty": f?,
                    "frequency_penalty": f?, "repetition_penalty": r?,
                    "adapter": a?, "stop": [int...]?,
                    "ignore_eos": bool?, "seed": s?, "logprobs": k?,
                    "prompt_logprobs": k?, "n": c?, "priority": p?,
                    "guided_regex": pattern?, "guided_json": true|schema?,
                    "guided_choice": [str...]?, "stream": true?}
                   guided_regex / guided_json constrain the output to
                   a regex / JSON (vLLM's guided decoding): the server
                   lowers the constraint to a token-level DFA riding
                   the compiled decode scan.  Constrained requests
                   decode via run_scan; a draft-loaded engine's spec
                   rounds resume once no constrained slot is active.
                   n > 1 returns c completions: token events carry
                   "index", the final event has "choices" (copies
                   admit incrementally and share the prompt via the
                   automatic prefix cache).
                   stream=true (default): chunked body, one JSON line
                   per event — coalesced window frames
                   {"tokens": [t, ...]} (one per run_scan window, the
                   engine-rate hot path) ... then
                   {"done": true, "tokens": [...], "finish_reason": r}
                   per_token=true restores the legacy per-token shape
                   {"token": t} (one line per token; logprobs requests
                   use it implicitly — the per-token stats need it).
                   stream=false: single JSON body (the final event).
  POST /v1/completions   OpenAI-compatible text completions (needs
                   --tokenizer): string or token-array "prompt",
                   max_tokens/temperature/top_p/n/seed/penalties/
                   logprobs/stop/echo, "response_format" {"type":
                   "json_object" | "json_schema"} and "guided_regex"
                   for guided decoding, "stream": true = SSE data:
                   chunks ending in [DONE] (stream_options
                   include_usage appends a usage-only chunk); usage
                   token accounting.
  POST /v1/chat/completions   chat variant: "messages" rendered by
                   the tokenizer's chat template; responses carry
                   message/delta objects in the chat wire shape.
  POST /migrate    INTERNAL (replica-to-replica via the router tier):
                   resume a prefill-class replica's bit-exact KV
                   checkpoint into a slot here and serve the
                   request's stream from where prefill left off —
                   the decode half of disaggregated serving.  The
                   body is the migrate codec's binary payload; a
                   ``prefill_only`` marker on the generate/OpenAI
                   endpoints produces it (see --replica-role).
  GET  /healthz    liveness ("ok").
  GET  /stats      engine + server counters (JSON).
  GET  /statz      one CHEAP load snapshot for the router tier
                   (queue depth, in-flight, free/total KV pages, shed
                   counts, scheduler health, replica role, migration
                   ledger) — fixed small schema, no Prometheus text
                   on the routing hot path.
  GET  /metrics    the same counters in Prometheus exposition format
                   (Accept: application/openmetrics-text adds trace-id
                   exemplars on the latency histograms).
  GET  /debug/traces[?trace_id=…]   per-request event timelines from
                   the flight recorder (index view without the param).
  GET  /debug/events[?since=…]      the raw journal after a wall-time
                   stamp (429 sheds, drops, grammar rejections, spans).

Tracing: requests may carry a W3C ``traceparent`` header; the server
continues that trace (or opens a fresh root) through admission, queue
wait, run_scan windows, and stream writes, echoes the id back in
``X-Trace-Id``/``traceparent`` response headers and OpenAI ``id``s,
and journals every hop in the flight recorder (dumped to
``--flight-record-dir`` on exit/SIGTERM).

Token ids in, token ids out by default: tokenization is the caller's
business and the engine's contract stays exact and model-agnostic.
``--tokenizer`` opts into the text surface server-side ("prompt"
strings, stop STRINGS with streaming holdback, "text" deltas) without
touching the compiled decode path.

Load shedding (vLLM's admission-control posture): HTTP traffic is
served by a FIXED worker pool (``--max-connections``) instead of a
thread per connection, the admission heap is bounded
(``--max-queue``), and overflow on either answers 429 +
``Retry-After`` instead of growing threads or heap without bound.
Per-request event queues are bounded too: a client that stops reading
its stream is disconnected (its events dropped, its slot released)
rather than buffering tokens forever — the documented slow-client
policy.
"""

from __future__ import annotations

import argparse
import bisect
import heapq
import itertools
import json
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.resilience import faults
from tpu_k8s_device_plugin.resilience.policy import (
    ResilienceMetrics,
    suppressed,
)

from .grammar import (
    json_value_regex,
    regex_to_dfa,
    schema_to_regex,
    token_bytes_of,
    token_dfa,
)
from .scheduler import (
    ADAPTIVE_WINDOW_FACTOR,
    DEFAULT_MAX_PACK,
    DEFAULT_PREFILL_BUDGET,
    IterationScheduler,
)
from .kv_pool import PagePoolExhausted
from .kv_tier import SessionStore, empty_tier_stats, sid_hash
from .migrate import (
    MIGRATE_CONTENT_TYPE,
    MigrateError,
    dump_payload,
    load_payload,
)
# TenantQuota moved to the jax-free qos module (the router enforces
# the same bucket semantics fleet-wide); re-exported here because
# embedders and the QoS suite import it from server
from .qos import TenantQuota, parse_tenant_quotas, resolve_quota
from .serving import ServingEngine

log = logging.getLogger(__name__)

# stats() keys that describe CURRENT state; everything else in stats()
# is monotonic and bridges to /metrics as a counter (``_total`` names)
_GAUGE_STATS = frozenset({
    "n_slots", "active_slots", "free_slots", "reserved_slots",
    "registered_prefixes", "pending_requests",
    "running_requests", "running_copies", "admitting_copies",
    "window", "http_workers", "connections_waiting", "max_queue",
    "grammar_patterns",
    "kv_pages", "kv_pages_free", "kv_pages_shared",
    "kv_page_size",
})

# scheduler knobs: a window is one compiled run_scan; shorter windows
# lower time-to-first-token for requests waiting in the admission
# queue, longer ones amortize host round-trips harder
DEFAULT_WINDOW = 8
_IDLE_POLL_S = 0.05

# scheduler crash containment: the supervisor restarts a crashed
# scheduler loop with capped exponential backoff; this many crashes in
# a row (no _SCHED_CRASH_RESET_S of clean running between them) and
# the server stops pretending — every in-flight AND future request
# answers 503 and /healthz fails, so an orchestrator restarts the pod
_SCHED_MAX_RESTARTS = 8
_SCHED_CRASH_RESET_S = 60.0
_SCHED_BACKOFF_MAX_S = 2.0

# client-supplied guided_regex length bound (ADVICE r5): pattern text
# is attacker-controlled on the HTTP surface, and subset construction
# is super-linear in it; server-lowered patterns (guided_json /
# guided_choice) are bounded by --max-grammar-states instead
_MAX_REGEX_LEN = 4096

# pre-encoded JSON-lines skeletons for the hot streaming path: one
# frame per run_scan window, built by byte concatenation — no dict, no
# json.dumps, no per-token work on either thread
_FRAME_PRE = b'{"tokens":['
_FRAME_POST = b']}\n'

# request-id source for the tracing spans; next() is atomic under the
# GIL, so handler threads draw ids without a lock
_RID_COUNTER = itertools.count(1)


def _tokens_frame(new, idx: int, n: int) -> bytes:
    """One pre-serialized coalesced window frame: the JSON line
    ``{"tokens": [...]}`` (index-tagged for n>1) as wire-ready bytes."""
    body = ",".join(map(str, new)).encode()
    if n > 1:
        return b'{"tokens":[%s],"index":%d}\n' % (body, idx)
    return _FRAME_PRE + body + _FRAME_POST


def _holdback(text: str, stop_strs) -> int:
    """How many trailing chars of *text* could still become a stop
    string (the longest proper stop-prefix *text* ends with) — the
    stream withholds them so a stop spanning two chunks never leaks."""
    h = 0
    for s in stop_strs:
        for k in range(min(len(s) - 1, len(text)), 0, -1):
            if text.endswith(s[:k]):
                h = max(h, k)
                break
    return h


class _DetokState:
    """Incremental detokenization for one stream copy (vLLM's
    prefix/read-offset scheme): each committed token decodes a BOUNDED
    trailing window — decode(ids[prefix:t]) minus the already-read
    decode(ids[prefix:read]) — so total tokenizer work is O(T · window)
    instead of the O(T^2) full-prefix re-decodes that used to run on
    the scheduler thread (ADVICE r4).  Offsets advance only when the
    tail is UTF-8 stable (no trailing U+FFFD), so a char split across
    tokens (BPE byte fallback) commits once its last byte arrives.

    ``text`` is the committed text; ``cum[t]`` is its length after
    token t committed — the token<->char map stop scanning needs."""

    __slots__ = ("prefix_off", "read_off", "text", "cum")

    def __init__(self):
        self.prefix_off = 0
        self.read_off = 0
        self.text = ""
        self.cum = [0]

    def feed(self, tok, ids, n: int) -> None:
        """Commit tokens up to count *n* (monotonic)."""
        while len(self.cum) - 1 < n:
            t = len(self.cum)
            full = tok.decode([int(i) for i in ids[self.prefix_off:t]])
            prefix = (tok.decode(
                [int(i) for i in ids[self.prefix_off:self.read_off]])
                if self.read_off > self.prefix_off else "")
            delta = full[len(prefix):]
            if delta and not delta.endswith("�"):
                self.text += delta
                self.prefix_off = self.read_off
                self.read_off = t
            self.cum.append(len(self.text))


def _find_stop(st: _DetokState, stop_strs, scanned_from: int):
    """Earliest-completing NEW stop match in the committed text past
    char offset *scanned_from* (earlier chars were proven match-free;
    the window re-covers max(len)-1 overlap chars so a stop spanning
    the boundary is still seen).  Returns (kept token count, truncated
    text) or (None, None): the kept tokens include the token that
    completed the match, the TEXT stops at the earliest start of any
    match visible by then (vLLM's default, stop string excluded)."""
    lo = max(0, scanned_from - (max(len(s) for s in stop_strs) - 1))
    best = None  # (end, pos) of the first COMPLETED new match
    for s in stop_strs:
        p = st.text.find(s, lo)
        while p >= 0:
            if p + len(s) > scanned_from:
                # first NEW completion of this stop; earlier (stale)
                # occurrences in the overlap window must not shadow it
                e = (p + len(s), p)
                if best is None or e < best:
                    best = e
                break
            p = st.text.find(s, p + 1)
    if best is None:
        return None, None
    end, pos = best
    # the text cut is the earliest START among matches completed by
    # *end* (a longer stop beginning earlier but ending later is not
    # yet complete and does not count — same rule as prefix scanning)
    for s in stop_strs:
        p = st.text.find(s, lo)
        while p >= 0 and p + len(s) <= end:
            pos = min(pos, p)
            p = st.text.find(s, p + 1)
    keep = bisect.bisect_left(st.cum, end)
    return keep, st.text[:pos]


def _usage(prompt_tokens: int, completion_tokens: int) -> dict:
    """The ONE usage object (streamed final chunk and unary response
    share it, so the two surfaces cannot drift)."""
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def _sse_envelope(rid: str, model_name: str, chat: bool, choices,
                  **extra) -> dict:
    """The one SSE chunk envelope (id/object/model/created) — every
    chunk shape (role, echo, deltas, final, usage) builds on it so the
    wire format cannot drift between sites."""
    return {
        "id": rid,
        "object": "chat.completion.chunk" if chat else "text_completion",
        "model": model_name,
        "created": int(time.time()),
        "choices": choices,
        **extra,
    }


def _openai_chunk(rid: str, model_name: str, ev: dict, sent: dict,
                  chat: bool = False, include_usage: bool = False):
    """One SSE chunk for a native event, or None for events the OpenAI
    stream does not carry (raw token ids).  *sent* accumulates the text
    streamed per choice index so the final chunk can flush whatever the
    deltas withheld — the native done event's "text" is authoritative
    (BPE holdback / rewritten-history cases deliberately under-stream;
    see _emit).  *chat* switches to the chat.completion.chunk shape
    (delta objects instead of text fields)."""
    def choice(idx, text, reason):
        if chat:
            delta = {"content": text} if text else {}
            return {"index": idx, "delta": delta,
                    "finish_reason": reason}
        return {"index": idx, "text": text, "finish_reason": reason}

    if "text" in ev and "done" not in ev:
        idx = ev.get("index", 0)
        sent[idx] = sent.get(idx, "") + ev["text"]
        return _sse_envelope(
            rid, model_name, chat,
            [choice(idx, ev["text"], None)],
            # OpenAI's include_usage contract: every chunk BEFORE the
            # final usage-only one carries "usage": null
            **({"usage": None} if include_usage else {}))
    if "done" in ev:
        chs = (ev["choices"] if "choices" in ev
               else [{**ev, "index": 0}])
        choices = []
        for c in chs:
            final = c.get("text", "")
            prev = sent.get(c["index"], "")
            if final.startswith(prev):
                tail = final[len(prev):]
            else:
                # a decode merge rewrote streamed history (rare, BPE):
                # resend the full authoritative text — duplicated
                # beats silently wrong
                tail = final
            choices.append(
                choice(c["index"], tail, c["finish_reason"]))
        return _sse_envelope(
            rid, model_name, chat, choices,
            **({"usage": None} if include_usage else {}))
    return None


def _openai_response(rid: str, model_name: str, req: "_Request",
                     done: dict, chat: bool = False,
                     echo_text: Optional[str] = None) -> dict:
    chs = done["choices"] if "choices" in done else [{**done, "index": 0}]
    choices = []
    completion_tokens = 0
    for c in sorted(chs, key=lambda c: c["index"]):
        completion_tokens += len(c["tokens"])
        lp = None
        if c.get("logprobs"):
            # trim the engine's top list to the OpenAI-requested count
            # (0 = chosen only; the engine always computes >= 1)
            n = req.openai_logprobs or 0
            if chat:
                # the chat wire shape: content list of per-token
                # records with nested top_logprobs objects
                lp = {"content": [
                    {"token": str(t), "logprob": r["logprob"],
                     "top_logprobs": [
                         {"token": str(i), "logprob": p}
                         for i, p in r["top_logprobs"][:n]]}
                    for t, r in zip(c["tokens"], c["logprobs"])]}
            else:
                lp = {
                    "token_logprobs": [
                        r["logprob"] for r in c["logprobs"]],
                    "top_logprobs": [
                        {str(i): p for i, p in r["top_logprobs"][:n]}
                        for r in c["logprobs"]],
                    "tokens": [str(t) for t in c["tokens"]],
                    "text_offset": None,
                }
                prec = (c.get("prompt_logprobs")
                        or done.get("prompt_logprobs"))
                if echo_text is not None and prec:
                    # echo+logprobs: prompt entries lead (first null),
                    # aligning the arrays with the echoed text
                    lp["tokens"] = [str(t) for t in req.tokens]                         + lp["tokens"]
                    lp["token_logprobs"] = [
                        None if r is None else r["logprob"]
                        for r in prec] + lp["token_logprobs"]
                    lp["top_logprobs"] = [
                        None if r is None else
                        {str(i): pr
                         for i, pr in r["top_logprobs"][:n]}
                        for r in prec] + lp["top_logprobs"]
        if chat:
            choices.append({
                "index": c["index"],
                "message": {"role": "assistant",
                            "content": c.get("text", "")},
                "finish_reason": c["finish_reason"],
                "logprobs": lp,
            })
        else:
            choices.append({
                "index": c["index"],
                # echo (OpenAI completions): the prompt text leads the
                # completion in every choice
                "text": (echo_text or "") + c.get("text", ""),
                "finish_reason": c["finish_reason"],
                "logprobs": lp,
            })
    return {
        "id": rid,
        "object": "chat.completion" if chat else "text_completion",
        "model": model_name,
        "created": int(time.time()),
        "choices": choices,
        "usage": _usage(len(req.tokens), completion_tokens),
    }


@dataclass
class _Request:
    tokens: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: float = 1.0
    min_p: float = 0.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    adapter: Optional[int] = None
    stop: Optional[List[int]] = None
    ignore_eos: bool = False
    seed: Optional[int] = None
    priority: int = 0                 # higher admits first
    _seq: int = 0                     # enqueue order (FIFO in a level)
    tenant: str = ""                  # QoS accounting identity
    _vft: float = 0.0                 # WFQ virtual finish time
    # preemption-by-page-eviction: copy idx -> engine checkpoint; the
    # scheduler resumes these before admitting anything new of ours
    preempted: dict = field(default_factory=dict)
    logprobs: Optional[int] = None
    prompt_logprobs: Optional[int] = None
    n: int = 1
    events: "queue.Queue" = field(default_factory=queue.Queue)
    cancelled: bool = False
    stream: bool = True               # streaming response requested
    per_token: bool = False           # legacy {"token": t} event shape
    openai: bool = False              # OpenAI route: text deltas only
    dropped: bool = False             # slow-client disconnect fired
    admitted: int = 0                 # copies admitted so far (of n)
    emitted: dict = field(default_factory=dict)   # copy index -> count
    choices: list = field(default_factory=list)   # finished copies
    budget_capped: bool = False
    # tokenizer-level surface (server-side; the engine stays ids-only):
    stop_strs: Optional[List[str]] = None
    detokenize: bool = False          # emit "text" deltas + final text
    text_sent: dict = field(default_factory=dict)  # idx -> emitted str
    detok: dict = field(default_factory=dict)  # idx -> _DetokState
    stop_scanned: dict = field(default_factory=dict)  # idx -> char off
    openai_logprobs: Optional[int] = None  # client-requested count
    echo: bool = False                # OpenAI completions echo
    echo_text: str = ""               # the ORIGINAL prompt text
    include_usage: bool = False       # stream_options.include_usage
    logit_bias: Optional[dict] = None      # {token id: bias}
    min_tokens: int = 0                    # eos/stop floor (vLLM)
    # guided decoding (vLLM's guided_regex / OpenAI response_format):
    # the handler thread compiles the pattern to a TokenDfa (cached by
    # pattern); the scheduler registers it with the engine at admit
    grammar_key: Optional[str] = None      # cache key (the pattern)
    grammar_tdfa: object = None            # compiled, pre-registration
    # request tracing (PR 3/4): the span observes
    # tpu_serve_request_seconds{outcome} exactly once per request and
    # leaves a request_id-tagged log line; t_arrival anchors the
    # queue-wait and TTFT histograms.  trace is the request's
    # TraceContext (continued from the caller's traceparent header or a
    # fresh root): it tags every span log line, flight-recorder event,
    # and OpenMetrics exemplar this request produces, and is echoed in
    # the response headers / OpenAI ids
    rid: str = ""
    t_arrival: float = 0.0
    span: object = None
    ttft_observed: bool = False
    trace: object = None
    # SLO/goodput accounting (PR 12): the request-supplied class name
    # (bounded to the declared policy set at record time — unknown
    # names land under the "other" label) and the observed TTFT the
    # terminal record is judged against
    slo_class: str = ""
    ttft_s: float = -1.0
    # disaggregated prefill/decode (router v2): prefill_only requests
    # run packed prefill, then the scheduler preempts the fresh slot
    # and the handler answers with the serialized checkpoint instead
    # of a token stream (the router ships it to a decode replica);
    # migrated marks a /migrate-resumed request on the decode side
    # (its quota was charged at the prefill replica — never twice)
    prefill_only: bool = False
    migrated: bool = False
    # session KV tiering (PR 20): the conversation key.  The scheduler
    # warm-promotes the session's parked KV before admission and parks
    # the finished slot back under it; session_tier records which tier
    # (if any) served the warm hit, so admission only trusts the
    # session donor when the store vouched for it
    session: str = ""
    session_tier: str = ""


class _PooledHTTPServer(HTTPServer):
    """HTTP server with a FIXED worker pool and a bounded accept
    queue, replacing ThreadingHTTPServer's thread-per-connection:
    *workers* connections are served concurrently, up to *workers*
    more wait in the hand-off queue, and anything beyond that is
    answered 429 + Retry-After immediately on the accept thread (one
    small pre-built response into a fresh socket's send buffer — it
    cannot block on the client).  Thread count is a constant whatever
    the burst, which is the point: the old thread-per-connection model
    grew without bound exactly when the server was least able to
    afford it."""

    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 128  # TCP accept backlog

    _REJECT_BODY = (json.dumps({"error": {
        "message": "connection limit reached; retry later",
        "type": "rate_limit_exceeded"}}) + "\n").encode()
    _REJECT = (b"HTTP/1.1 429 Too Many Requests\r\n"
               b"Content-Type: application/json\r\n"
               b"Retry-After: 1\r\n"
               b"Content-Length: %d\r\n"
               b"Connection: close\r\n\r\n" % len(_REJECT_BODY)
               ) + _REJECT_BODY

    def __init__(self, addr, handler, workers: int, shed_counter=None,
                 recorder=None):
        super().__init__(addr, handler)
        self._conns: "queue.Queue" = queue.Queue(maxsize=workers)
        # 429s shed at accept: an obs counter child when the owning
        # EngineServer wires one (tpu_serve_shed_total{reason=
        # "connections"}), a plain int for standalone embedders
        self._shed = shed_counter
        self._recorder = recorder
        self._rejected_fallback = 0
        self._pool = [
            threading.Thread(target=self._worker,
                             name=f"serve-http-{i}", daemon=True)
            for i in range(workers)]
        for t in self._pool:
            t.start()

    def process_request(self, request, client_address):
        """Accept thread: hand the connection to the pool or shed it."""
        try:
            self._conns.put_nowait((request, client_address))
        except queue.Full:
            if self._shed is not None:
                self._shed.inc()
            else:
                self._rejected_fallback += 1
            if self._recorder is not None:
                # no request (and so no trace) exists yet at accept
                # time: the shed is still a journal-worthy lifecycle
                # event for the post-mortem timeline
                self._recorder.record("tpu_serve_shed",
                                      reason="connections",
                                      peer=str(client_address[0]))
            try:
                request.settimeout(0.5)
                request.sendall(self._REJECT)
                # drain whatever request bytes already arrived so the
                # close does not RST the 429 out of the peer's buffer
                try:
                    request.recv(1 << 20)
                except OSError:
                    pass
            except OSError:
                pass
            self.shutdown_request(request)

    def _worker(self):
        while True:
            item = self._conns.get()
            if item is None:
                return
            request, client_address = item
            try:
                self.finish_request(request, client_address)
            except Exception:
                self.handle_error(request, client_address)
            finally:
                self.shutdown_request(request)

    @property
    def connections_rejected(self) -> int:
        return (int(self._shed.value) if self._shed is not None
                else self._rejected_fallback)

    def pool_stats(self) -> dict:
        return {
            "http_workers": len(self._pool),
            "connections_waiting": self._conns.qsize(),
            "connections_rejected": self.connections_rejected,
        }

    def server_close(self):
        super().server_close()
        # best-effort pool drain: workers mid-stream see the
        # scheduler's shutdown 503 and exit their connection; the
        # sentinels release the idle ones (daemon threads back-stop)
        for _ in self._pool:
            try:
                self._conns.put_nowait(None)
            except queue.Full:
                break
        for t in self._pool:
            t.join(timeout=1)


class EngineServer:
    """Scheduler + HTTP surface around one ServingEngine.

    >>> srv = EngineServer(engine, max_new_tokens=64).start(port=0)
    >>> # curl -N -d '{"tokens":[1,2,3]}' http://host:port/generate
    >>> srv.stop()
    """

    def __init__(self, engine: ServingEngine,
                 max_new_tokens: int = 64,
                 window: int = DEFAULT_WINDOW,
                 tokenizer=None,
                 token_bytes: Optional[List[bytes]] = None,
                 max_grammars: int = 64,
                 max_queue: int = 1024,
                 max_connections: int = 64,
                 max_events: int = 256,
                 max_grammar_states: int = 8192,
                 client_timeout: float = 120.0,
                 flight_record_dir: Optional[str] = None,
                 flight_record_capacity: int = 4096,
                 interleave: bool = True,
                 prefill_chunks: int = DEFAULT_PREFILL_BUDGET,
                 schedule_watchdog_s: float = 0.0,
                 tenant_quotas: Optional[dict] = None,
                 packed_prefill: bool = True,
                 overlap_dispatch: bool = True,
                 max_pack: int = DEFAULT_MAX_PACK,
                 slo_policies: Optional[dict] = None,
                 slo_window_s: float = 60.0,
                 profile_dir: Optional[str] = None,
                 flight_dump_keep: int = 20,
                 replica_role: str = "mixed",
                 alert_rules: Optional[list] = None,
                 alert_interval_s: float = 5.0,
                 alert_window_scale: float = 1.0,
                 incident_dir: Optional[str] = None,
                 profiler_hz: float = 19.0,
                 session_tier: bool = False,
                 session_dir: Optional[str] = None,
                 session_host_mb: int = 256,
                 session_disk_keep: int = 512,
                 session_idle_s: float = 30.0,
                 session_host_idle_s: float = 120.0,
                 session_seed: int = 0):
        """*tokenizer* (anything with ``encode(str) -> List[int]`` and
        ``decode(List[int]) -> str``, e.g. a transformers tokenizer)
        unlocks the text-level surface: ``"prompt"`` strings, STRING
        entries in ``"stop"`` (vLLM's stop strings — matched against
        the detokenized stream, held back across chunk boundaries),
        and ``"text"`` deltas in the response.  Without it the server
        speaks token ids only, as before.

        *max_queue* bounds the admission heap and *max_connections*
        the HTTP worker pool (each overflow answers 429 +
        Retry-After); *max_events* bounds each request's event queue
        (a client that stops draining is disconnected and its slot
        released); *max_grammar_states* rejects guided-decoding
        patterns whose char-DFA exceeds that many states BEFORE the
        [N, V] token table is built; *client_timeout* is the
        per-connection socket timeout so a stuck peer frees its pool
        worker."""
        if engine.max_new_tokens is not None:
            raise ValueError(
                "pass per-request budgets to EngineServer, not the "
                "engine: an engine-wide max_new_tokens would retire "
                "slots behind the scheduler's back at the wrong budget")
        if window < 1:
            raise ValueError("window must be >= 1")
        self.engine = engine
        self.default_max_new = max_new_tokens
        self.window = window
        self.tokenizer = tokenizer
        # guided decoding: per-token byte strings let the server lower
        # per-request regex/JSON constraints to the engine's TokenDfa.
        # Explicit *token_bytes* wins; otherwise derived lazily from
        # the tokenizer on the first grammar request.  The pattern ->
        # TokenDfa cache is bounded (max_grammars) because each
        # distinct pattern also occupies rows in the engine's combined
        # grammar table for the engine's lifetime.
        self._token_bytes = token_bytes
        self.max_grammars = max_grammars
        if max_queue < 1 or max_connections < 1 or max_events < 8:
            raise ValueError(
                "max_queue/max_connections must be >= 1 and "
                "max_events >= 8")
        self.max_queue = max_queue
        self.max_connections = max_connections
        self.max_events = max_events
        self.max_grammar_states = max_grammar_states
        self.client_timeout = client_timeout
        # disaggregated serving role (router v2): advertised through
        # /register and /statz so the router routes phase-aware.
        # prefill/decode classes need the paged pool — migration IS
        # preempt-on-A/resume-on-B, and only paged slots checkpoint
        if replica_role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"replica_role {replica_role!r} must be mixed, "
                "prefill, or decode")
        if replica_role != "mixed" and not getattr(
                engine, "kv_paging", False):
            raise ValueError(
                f"replica_role={replica_role!r} needs a paged engine "
                "(kv_paging=True): KV migration is preempt/resume, "
                "which only the paged pool checkpoints")
        self.replica_role = replica_role
        self._grammar_tdfas: dict = {}    # pattern -> TokenDfa
        self._grammar_gids: dict = {}     # pattern -> engine gid
        self._glock = threading.Lock()
        # priority heap (vLLM's priority scheduling): higher-priority
        # requests admit first, FIFO within a priority level (the
        # monotonic sequence number breaks ties).  Guarded by _lock —
        # handler threads push, only the scheduler pops.
        self._pending: list = []
        self._pending_seq = 0
        self._lock = threading.Lock()
        self._work = threading.Event()    # set on every enqueue
        self._running: dict = {}          # slot -> (_Request, copy idx)
        self._head: Optional[_Request] = None  # partially admitted n>1
        self._stop = threading.Event()
        self._httpd: Optional["_PooledHTTPServer"] = None
        self._scheduler: Optional[threading.Thread] = None
        self._requests_served = 0
        self._requests_rejected = 0
        # -- observability (PR 3): the serving registry -------------------
        # request spans + latency histograms; /metrics renders THIS via
        # the shared obs renderer (the old hand-rolled loop is gone).
        # The 429-shed and slow-client-drop ad-hoc ints are promoted to
        # real counters; stats() reads the counters back so the JSON
        # and Prometheus surfaces cannot drift.
        self.registry = obs.Registry()
        reg = self.registry
        self._m_ttft = reg.histogram(
            "tpu_serve_ttft_seconds",
            "Time from request arrival to its first generated token "
            "(queue wait + prefill + first window included).",
            buckets=obs.LATENCY_BUCKETS_S)
        self._m_token = reg.histogram(
            "tpu_serve_token_seconds",
            "Per-token decode latency: each run_scan window observes "
            "window_time/tokens once per token per stream.",
            buckets=obs.FAST_BUCKETS_S)
        self._m_request = reg.histogram(
            "tpu_serve_request_seconds",
            "End-to-end request latency by outcome (ok, rejected, "
            "throttled, dropped, cancelled, shutdown).",
            ("outcome",), buckets=obs.LATENCY_BUCKETS_S)
        self._m_queue_wait = reg.histogram(
            "tpu_serve_queue_wait_seconds",
            "Time a request waited in the admission heap before its "
            "first copy was admitted.", buckets=obs.LATENCY_BUCKETS_S)
        self._m_admit = reg.histogram(
            "tpu_serve_admit_seconds",
            "One engine admit (prompt prefill / prefix-cache splice).",
            buckets=obs.LATENCY_BUCKETS_S)
        self._m_stream_write = reg.histogram(
            "tpu_serve_stream_write_seconds",
            "One chunked stream write (>= 1 coalesced window frames).",
            buckets=obs.FAST_BUCKETS_S)
        self._m_shed = reg.counter(
            "tpu_serve_shed_total",
            "Load shed with 429 + Retry-After, by admission surface.",
            ("reason",))
        self._shed_conns = self._m_shed.labels(reason="connections")
        self._shed_queue = self._m_shed.labels(reason="queue")
        self._shed_quota = self._m_shed.labels(reason="quota")
        self._m_dropped = reg.counter(
            "tpu_serve_slow_client_drops_total",
            "Clients disconnected for not draining their stream "
            "(bounded event queue overflowed).")
        self._m_abandons = reg.counter(
            "tpu_serve_client_abandons_total",
            "Requests whose CLIENT disconnected mid-request (reset "
            "or broken pipe seen by the handler) — the client-side "
            "mirror of the slow-client drops the server initiates.")
        # -- paged KV pool + multi-tenant QoS -----------------------------
        # Pool occupancy/sharing gauges and the preemption/CoW/eviction
        # counters refresh from engine stats at scrape time; they render
        # (as zeros) on contiguous engines too, so dashboards see one
        # schema.  Tenant quotas: token buckets over estimated tokens,
        # weighted fair queueing in the admission heap (vft ordering
        # WITHIN a priority level), preemption-by-page-eviction when the
        # paged pool runs dry — 429s become per-tenant policy instead of
        # the global --max-queue constant.
        self._m_kv_pages_free = reg.gauge(
            "tpu_serve_kv_pages_free",
            "Free physical pages in the paged KV pool (0 when paging "
            "is off).")
        self._m_kv_pages_shared = reg.gauge(
            "tpu_serve_kv_pages_shared",
            "Physical KV pages referenced by more than one slot "
            "(copy-on-write prefix sharing).")
        self._m_kv_preempt = reg.counter(
            "tpu_serve_kv_preemptions_total",
            "Slots preempted by page eviction (KV checkpointed to "
            "host, pages freed, request re-queued).")
        self._m_kv_cow = reg.counter(
            "tpu_serve_kv_cow_copies_total",
            "Copy-on-write page copies (an append into a shared "
            "prefix page).")
        self._m_prefix_evict = reg.counter(
            "tpu_serve_prefix_evictions_total",
            "Prefix-registry/parked-donor records evicted by the LRU "
            "cap or pool-pressure reclaim.")
        # -- disaggregated prefill/decode migration -----------------------
        # out = prefill-only requests exported as a checkpoint (this
        # replica ran packed prefill, the router shipped the KV state
        # on); in = /migrate checkpoints resumed here.  Both children
        # materialize at boot so /statz and the family stay lock-step
        # from the first scrape, role notwithstanding
        self._m_migrations = reg.counter(
            "tpu_serve_migrations_total",
            "KV-state migrations by direction: out = prefill-only "
            "admissions preempted and exported to the router, in = "
            "/migrate checkpoints resumed on this replica.",
            ("direction",))
        self._mig_out = self._m_migrations.labels(direction="out")
        self._mig_in = self._m_migrations.labels(direction="in")
        self._mig_out.inc(0)
        self._mig_in.inc(0)
        # -- ragged packed prefill + warmup -------------------------------
        self._m_packed_reqs = reg.counter(
            "tpu_serve_packed_prefill_requests_total",
            "Admissions whose prefill rode at least one ragged packed "
            "(batched) extend dispatch.")
        self._m_packed_pad = reg.counter(
            "tpu_serve_packed_prefill_pad_tokens_total",
            "Zero-pad token rows computed by packed prefill dispatches "
            "(tail-chunk grid padding — the packing waste metric).")
        # -- fused decode loop (PR 17) ------------------------------------
        # harvest-side visibility for the fused window path: how many
        # windows ran with the on-device boundary carry, and how many
        # post-finish steps those windows burned (the adaptive-window
        # headroom signal).  Rendered from boot on unfused engines too
        # (zeros), so the dashboard schema is mode-independent.
        self._m_fused_windows = reg.counter(
            "tpu_serve_fused_windows_total",
            "Decode windows dispatched with the fused on-device "
            "boundary carry (eos/stop/budget detected in-scan).")
        self._m_fused_trunc = reg.counter(
            "tpu_serve_fused_truncated_tokens_total",
            "Tokens computed after a slot's on-device finish boundary "
            "and discarded at harvest (post-finish window burn).")
        self._m_warmup = reg.gauge(
            "tpu_serve_warmup_seconds",
            "Wall seconds warm_scheduler spent pre-compiling, by "
            "phase (scan = adaptive-window variants, packed_prefill = "
            "the packed shape set, total = everything).  With a warm "
            "--compile-cache-dir these collapse to cache-hit loads.",
            ("phase",))
        reg.on_collect(self._collect_kv)
        self.tenant_quotas = dict(tenant_quotas or {})
        self._qos = bool(self.tenant_quotas)
        self._vtime = 0.0              # WFQ virtual clock (under _lock)
        # -- SLO / goodput accounting (PR 12) -----------------------------
        # every terminal request lands in tpu_slo_requests_total{class,
        # tenant,met}; the rolling-window goodput/burn-rate gauges and
        # the /statz goodput block come from the same accountant, so
        # the router tier and the dashboards read one truth.  Always
        # on: without --slo the default interactive/batch policies
        # classify (generously) rather than nothing being measured
        self._slo = obs.SLOAccountant(
            reg, policies=slo_policies,
            tenants=self.tenant_quotas.keys(),
            window_s=slo_window_s)
        # -- continuous profiling hook (PR 12) ----------------------------
        # GET /debug/profile?seconds=N dumps a jax.profiler trace to
        # --profile-dir; single-flight guarded (a second request while
        # one is capturing answers 409 instead of corrupting the trace)
        self.profile_dir = profile_dir
        self._profile_lock = threading.Lock()
        self._m_profile = reg.counter(
            "tpu_serve_profile_captures_total",
            "Profiler traces captured via /debug/profile (dumped to "
            "--profile-dir).")
        self._m_profile.inc(0)  # render from boot: one schema
        # crash containment (PR 5): a scheduler-thread death is
        # counted, journaled, and survived (supervised restart) —
        # never a silent hang with clients blocked on event queues
        self._m_sched_crashes = reg.counter(
            "tpu_serve_scheduler_crashes_total",
            "Engine-scheduler loop crashes caught by the supervisor.")
        self._m_sched_restarts = reg.counter(
            "tpu_serve_scheduler_restarts_total",
            "Engine-scheduler restarts after a crash (crashes past "
            "the restart budget kill the server instead).")
        self._sched_dead = False
        # -- tracing + flight recorder (PR 4) -----------------------------
        # every span end and lifecycle event (sheds, drops, grammar
        # rejections) lands in this bounded ring, stamped with the
        # request's trace-id; /debug/traces and /debug/events read it,
        # and --flight-record-dir dumps it on exit/SIGTERM
        self.recorder = obs.FlightRecorder(
            capacity=flight_record_capacity, registry=reg,
            dump_keep=flight_dump_keep)
        self.flight_record_dir = flight_record_dir
        if flight_record_dir:
            self.recorder.install_dump_handlers(flight_record_dir)
        # -- in-process retention + alerting (PR 18) ----------------------
        # a bounded TSDB samples this registry on a background tick
        # (GET /debug/query reads it back), and the evaluator derives
        # the SRE multi-window multi-burn-rate rules from every SLO
        # class above — page at 14.4x over the short+long window pair,
        # ticket at 1x over six hours — plus whatever --alert-rules
        # hand-writes.  Firing pages surface on /alerts and in statz(),
        # which is how the fleet autoscaler learns reason=alert.
        reg.on_collect(self._bridge_stats)
        self.scrape_meta = obs.ScrapeMeta(reg)
        self.tsdb = obs.TSDB(reg)
        self.alert_interval_s = float(alert_interval_s)
        _rules = obs.burn_rate_rules(
            self._slo.policies, window_scale=alert_window_scale)
        _rules.extend(alert_rules or ())
        self.alerts = obs.AlertEvaluator(
            self.tsdb, _rules, recorder=self.recorder)
        # -- continuous profiling + incident bundles (PR 19) --------------
        # the always-on sampler (GET /debug/pprof) tags every stack
        # sample with the scheduler's live phase and the in-flight
        # count; when a page-severity alert fires, the incident
        # manager snapshots everything (journal, TSDB, profile ring,
        # statz, slowest SLO-missed traces) into one atomic directory
        # under --incident-dir — the post-mortem writes itself
        self.profiler = obs.SamplingProfiler(
            reg, hz=profiler_hz,
            phase_fn=lambda: self._sched.phase,
            active_fn=lambda: len(self._running))
        self.incident_dir = incident_dir
        self._incidents: Optional[obs.IncidentManager] = None
        if incident_dir:
            self._incidents = obs.IncidentManager(
                incident_dir, self.alerts, registry=reg,
                recorder=self.recorder, tsdb=self.tsdb,
                profiler=self.profiler,
                collectors={"statz.json": self.statz,
                            "traces.json": self.slo_miss_traces})
        # -- iteration scheduler (continuous batching) --------------------
        # the engine's sole driver: a unified work queue of decode
        # windows and prefill chunks.  With interleave on (default),
        # prefill chunks, new admissions, and admission finishes are
        # dispatched while a decode window runs on the device — a
        # request admitted mid-window starts prefilling before that
        # window closes, and admission no longer stalls running
        # streams.  interleave=False reproduces the old
        # admit-fully-then-scan cadence (outputs are bit-identical
        # either way — the equivalence tests pin it).
        self.interleave = bool(interleave)
        # ragged packed prefill + dispatch-ahead overlap (both default
        # on; outputs are byte-identical either way — the packed/
        # overlap equivalence suites pin it): packing batches
        # concurrent admissions' chunks into one extend, overlap keeps
        # window N+1 on the device while this thread streams window N
        self.packed_prefill = bool(packed_prefill)
        self.overlap_dispatch = bool(overlap_dispatch)
        self._sched = IterationScheduler(
            engine, window=window, interleave=interleave,
            prefill_budget=prefill_chunks, pull=self._pull_ticket,
            on_admit=self._bind_admitted,
            budget_hint=self._budget_hint,
            packed_prefill=packed_prefill, max_pack=max_pack,
            overlap=overlap_dispatch, registry=reg,
            recorder=self.recorder)
        self._tickets: dict = {}   # Ticket -> (_Request, copy idx)
        # optional hang containment for the scheduler loop: a watchdog
        # fails an iteration stuck past the deadline (WatchdogTimeout
        # -> the crash supervisor 503s in-flight requests and
        # restarts).  Off by default: a first-window compile can
        # legitimately take tens of seconds, so the knob is for
        # operators (and the chaos harness) who know their steady
        # state.
        self._sched_watchdog = None
        if schedule_watchdog_s > 0:
            from tpu_k8s_device_plugin import resilience

            self._sched_watchdog = resilience.Watchdog(
                op="serve.schedule", timeout_s=schedule_watchdog_s,
                metrics=resilience.ResilienceMetrics(reg),
                recorder=self.recorder)
        # -- session KV tiering (PR 20) -----------------------------------
        # device-parked conversations demote to host RAM and a
        # crash-safe spill dir on idleness and pressure, and promote
        # back when the session returns; every transition degrades to
        # re-prefill, never a failed request
        self._session_store: Optional[SessionStore] = None
        if session_tier:
            if not getattr(engine, "kv_paging", False):
                raise ValueError(
                    "session tiering needs a paged engine "
                    "(kv_paging=True): tier transitions are the paged "
                    "checkpoint/restore path")
            if not getattr(engine, "auto_prefix", False):
                raise ValueError(
                    "session tiering needs auto_prefix=True: warm "
                    "resume rides the automatic prefix match")
            self._session_store = SessionStore(
                engine, spill_dir=session_dir,
                host_cap_bytes=session_host_mb * 1024 * 1024,
                disk_keep=session_disk_keep,
                device_idle_s=session_idle_s,
                host_idle_s=session_host_idle_s,
                seed=session_seed, registry=reg,
                recorder=self.recorder,
                rmetrics=ResilienceMetrics(reg))
        # preemption-by-page-eviction: the paged engine escalates a
        # failed page allocation to this policy (scheduler thread) —
        # demote an idle parked session first (its pages are the
        # cheapest to reclaim), then checkpoint the lowest-priority
        # running slot to host, free its pages, re-queue its request
        # for later resume
        if getattr(engine, "kv_paging", False):
            engine.set_preempt_cb(self._page_pressure)

    def _page_pressure(self, exclude_slot: int = -1) -> bool:
        """Page-pressure escalation order: parked sessions yield
        before running requests are preempted."""
        if self._session_store is not None and \
                self._session_store.demote_for_pages(time.monotonic()):
            return True
        return self._preempt_for_pages(exclude_slot)

    def _collect_kv(self) -> None:
        """Scrape-time refresh of the KV-pool/QoS/packed-prefill
        families from engine stats (counters _set to the engine's
        monotonic values)."""
        st = self.engine.stats()
        self._m_kv_pages_free.set(st.get("kv_pages_free", 0))
        self._m_kv_pages_shared.set(st.get("kv_pages_shared", 0))
        self._m_kv_preempt._set(st.get("kv_preemptions", 0))
        self._m_kv_cow._set(st.get("kv_cow_copies", 0))
        self._m_prefix_evict._set(st.get("prefix_evictions", 0))
        self._m_packed_reqs._set(st.get("packed_prefill_requests", 0))
        self._m_packed_pad._set(st.get("packed_prefill_pad_tokens", 0))
        self._m_fused_windows._set(st.get("fused_windows", 0))
        self._m_fused_trunc._set(st.get("fused_truncated_tokens", 0))

    def _resolve_quota(self, tenant: str) -> Optional["TenantQuota"]:
        """Per-tenant QoS state; the ``*`` spec is a TEMPLATE — each
        unknown tenant gets its own bucket and WFQ chain cloned from
        it (shared state would let one tenant drain another's
        budget).  Caller holds ``_lock``."""
        return resolve_quota(self.tenant_quotas, tenant)

    def _preempt_for_pages(self, exclude_slot: int = -1) -> bool:
        """The engine's page-pressure escalation (scheduler thread):
        preempt the lowest-priority, most-recently-admitted running
        copy (never *exclude_slot* — the slot the engine is trying to
        grow).  The evicted copy's checkpoint rides its request back
        into the admission heap; the pull path resumes it when pages
        free up.  Returns False when nothing is preemptible."""
        cands = [
            (req.priority, i, slot, req, idx)
            for i, (slot, (req, idx)) in
            enumerate(self._running.items())
            if slot != exclude_slot and not req.cancelled
        ]
        if not cands:
            return False
        cands.sort(key=lambda c: (c[0], -c[1]))
        _, _, slot, req, idx = cands[0]
        try:
            state = self.engine.preempt(slot)
        except (RuntimeError, ValueError):
            return False
        del self._running[slot]
        req.preempted[idx] = state
        self.recorder.record("tpu_serve_kv_preempt", trace=req.trace,
                             rid=req.rid, slot=slot, copy=idx,
                             tenant=req.tenant)
        with self._lock:
            self._pending_seq += 1
            heapq.heappush(
                self._pending,
                (-req.priority, req._vft, self._pending_seq, req))
        self._work.set()
        return True

    def _mark(self, req: "_Request", name: str, duration_s: float,
              **attrs) -> None:
        """One traced sub-operation (queue wait, admit, window, stream
        write): a flight-recorder event plus a span-style log line, both
        carrying the request's trace-id — the breadcrumbs /debug/traces
        stitches into a per-request timeline.  The matching histogram
        observation stays at the call site (it may be a bulk observe)."""
        self.recorder.record(name, trace=req.trace, rid=req.rid,
                             duration_s=duration_s, **attrs)
        if log.isEnabledFor(logging.DEBUG):
            tid = req.trace.trace_id if req.trace is not None else ""
            extra = " ".join(f"{k}={v}" for k, v in attrs.items())
            log.debug("span=%s request_id=%s trace_id=%s "
                      "duration_s=%.6f%s", name, req.rid, tid,
                      duration_s, f" {extra}" if extra else "")

    # promoted ad-hoc ints: reads must keep working (tests, embedders)
    # while the obs counters are the single source of truth
    @property
    def _requests_throttled(self) -> int:
        return int(self._shed_queue.value)

    @property
    def _requests_dropped(self) -> int:
        return int(self._m_dropped.value)

    def _finish_request(self, req: _Request, outcome: str) -> None:
        """Terminal accounting: end the request span exactly once
        (observes tpu_serve_request_seconds{outcome} and logs the
        request-id line) and record the SLO verdict — goodput counts
        every terminal request, and a shed/dropped/crashed one never
        meets its SLO.  Safe to race — Span.end is idempotent, and
        handler threads (cancel paths) may race the scheduler."""
        sp = req.span
        if sp is not None:
            req.span = None
            total_s = sp.end(outcome=outcome)
            if outcome == "migrated":
                # the request is still IN FLIGHT fleet-wise: the
                # decode replica that resumed the checkpoint records
                # the one true SLO verdict when the stream terminates
                return
            # requests that never declared a class derive one from
            # their shape: streaming callers care about TTFT
            # (interactive), unary callers about the deadline (batch)
            met = self._slo.record(
                req.slo_class or None, req.tenant,
                ttft_s=req.ttft_s if req.ttft_s >= 0 else None,
                total_s=total_s, ok=outcome == "ok",
                fallback="interactive" if req.stream else "batch")
            if not met:
                # per-miss journal marker (PR 19): the incident
                # bundler joins these against the trace ring to stitch
                # "the slowest requests that missed their SLO" without
                # re-deriving policy verdicts offline
                self.recorder.record(
                    "tpu_serve_slo_miss", trace=req.trace,
                    rid=req.rid, duration_s=total_s, outcome=outcome,
                    slo_class=req.slo_class or "")

    def _note_client_abandon(self, req: _Request) -> None:
        """The CLIENT vanished mid-request (reset / broken pipe on
        its connection).  Count + journal it so a bench/replay
        ``abandoned`` outcome has a server-side record to join
        against — distinct from the slow-client drop, which is the
        SERVER's decision (this path was previously invisible: the
        request finished as a bare ``cancelled`` with no way to tell
        a user Ctrl-C from an operator cancel)."""
        self._m_abandons.inc()
        self.recorder.record("tpu_serve_client_abandon",
                             trace=req.trace, rid=req.rid)

    # -- scheduler (sole owner of the engine) -------------------------------

    def _pull_ticket(self):
        """The iteration scheduler's intake: pop the next request copy
        off the priority heap and hand it over as an admission ticket
        (``begin_admit`` under the hood — validation errors 400 here,
        prefill runs later, interleaved with decode).  A request with
        n > 1 admits one ticket per copy, INCREMENTALLY as slots free
        (continuous batching, not gang scheduling) — sibling copies
        share the prompt, so the automatic prefix cache turns every
        copy after the first into a tail-only prefill.  Returns None
        when nothing is waiting."""
        eng = self.engine
        while True:
            with self._lock:
                head = self._head
                top = self._pending[0] if self._pending else None
                if (head is not None and top is not None
                        and -top[0] > head.priority):
                    # a strictly higher-priority arrival preempts the
                    # remaining copies of a partially-admitted n>1
                    # request — the head goes back into the heap at
                    # its ORIGINAL position within its level
                    req = heapq.heappop(self._pending)[-1]
                    heapq.heappush(
                        self._pending,
                        (-head.priority, head._vft, head._seq, head))
                    self._head = None
                elif head is not None:
                    req, self._head = head, None
                elif top is not None:
                    req = heapq.heappop(self._pending)[-1]
                else:
                    return None
                # WFQ virtual clock follows the served frontier
                if self._qos and req._vft > self._vtime:
                    self._vtime = req._vft
            if req.cancelled:
                # preempted checkpoints of a cancelled request are
                # dropped (their pages were freed at preemption)
                req.preempted.clear()
                continue
            if req.preempted:
                # resume an evicted copy before admitting anything
                # new of this request: the checkpoint already holds
                # its tokens — re-queueing it behind fresh work would
                # strand a half-finished stream
                idx = next(iter(req.preempted))
                state = req.preempted[idx]
                if state.get("gstate_rel", False):
                    # a MIGRATED checkpoint carries grammar state in
                    # grammar-local form (absolute table offsets are
                    # per-engine): register the pattern here (cached)
                    # and re-home the state onto our combined table
                    try:
                        rel = int(state["gstate"])
                        if rel >= 0:
                            if req.grammar_key is None:
                                raise ValueError(
                                    "migrated checkpoint carries "
                                    "grammar state but the request "
                                    "declares no grammar")
                            state["gstate"] = eng.grammar_abs(
                                int(self._ensure_grammar(req)), rel)
                        state.pop("gstate_rel", None)
                    except ValueError as e:
                        req.preempted.clear()
                        self._requests_rejected += 1
                        self._push(req, {"error": str(e), "code": 400})
                        self._finish_request(req, "rejected")
                        continue
                try:
                    slot = eng.resume(state)
                except PagePoolExhausted:
                    # still no capacity: back on the heap, stop
                    # pulling this round (decode progress frees pages)
                    with self._lock:
                        self._pending_seq += 1
                        heapq.heappush(
                            self._pending,
                            (-req.priority, req._vft,
                             self._pending_seq, req))
                    return None
                except RuntimeError:
                    # no free slot this round: requeue, stop pulling
                    with self._lock:
                        self._pending_seq += 1
                        heapq.heappush(
                            self._pending,
                            (-req.priority, req._vft,
                             self._pending_seq, req))
                    return None
                except (ValueError, TypeError, KeyError) as e:
                    # cross-process payloads can be arbitrarily wrong
                    # (shape/dtype skew between replica builds): a
                    # bad one must 400 its own request, not take the
                    # scheduler thread down with it
                    req.preempted.clear()
                    self._requests_rejected += 1
                    self._push(req, {
                        "error": "migrated checkpoint failed to "
                                 f"resume: {e}", "code": 400})
                    self._finish_request(req, "rejected")
                    continue
                del req.preempted[idx]
                self._running[slot] = (req, idx)
                self.recorder.record(
                    "tpu_serve_kv_resume", trace=req.trace,
                    rid=req.rid, slot=slot, copy=idx,
                    tenant=req.tenant)
                if req.preempted:
                    with self._lock:
                        self._pending_seq += 1
                        heapq.heappush(
                            self._pending,
                            (-req.priority, req._vft,
                             self._pending_seq, req))
                continue
            if self._sched.packing_conflict(req.tokens):
                # an in-flight packed admission shares this prompt's
                # leading chunk: beginning NOW would forfeit the APC
                # match a serial admission gets (the donor has not
                # spliced yet).  Defer — the pending ticket lands
                # within a few iterations and the re-pull hits the
                # warm donor.  Sibling copies of an n>1 request defer
                # the same way (copy 0 is the in-flight conflict), so
                # their tail-only prefill economics are unchanged by
                # packing.
                if req.admitted > 0:
                    self._head = req    # partially-admitted n>1 head
                else:
                    with self._lock:
                        heapq.heappush(
                            self._pending,
                            (-req.priority, req._vft, req._seq, req))
                return None
            try:
                if not req.budget_capped:
                    # cap the admission budget so prompt + generation
                    # fits the cache; the per-request budget applies
                    if (len(req.tokens) + req.max_new_tokens
                            > eng.model.max_len):
                        budget = eng.model.max_len - len(req.tokens)
                        if budget < 1:
                            raise ValueError(
                                f"prompt ({len(req.tokens)} tokens) "
                                f"leaves no room to generate within "
                                f"max_len {eng.model.max_len}")
                        req.max_new_tokens = budget
                    req.budget_capped = True
                gid: object = False
                if req.grammar_key is not None:
                    # engine-side registration happens HERE because the
                    # scheduler is the engine's sole owner; the pattern
                    # cache makes it once-per-pattern, so the steady
                    # state is a dict lookup
                    gid = self._ensure_grammar(req)
                if req.admitted == 0 and req.t_arrival:
                    wait_dt = time.perf_counter() - req.t_arrival
                    self._m_queue_wait.observe(wait_dt)
                    self._mark(req, "tpu_serve_queue_wait", wait_dt)
                if (req.session and req.admitted == 0 and req.n == 1
                        and not req.migrated and not req.prefill_only
                        and self._session_store is not None):
                    # warm-promote the conversation's parked KV ahead
                    # of admission; a host/disk restore lands in its
                    # own parked slot, so one must stay free for THIS
                    # admission.  Any failure leaves session_tier
                    # empty and the request re-prefills — tiering
                    # never fails a request.
                    req.session_tier = self._session_store.prepare(
                        req.session, time.monotonic(),
                        can_restore=len(eng.free_slots()) >= 2)
                ticket = self._sched.begin(
                    req.tokens, temperature=req.temperature,
                    top_k=req.top_k, top_p=req.top_p,
                    min_p=req.min_p,
                    presence_penalty=req.presence_penalty,
                    frequency_penalty=req.frequency_penalty,
                    repetition_penalty=req.repetition_penalty,
                    adapter=req.adapter, stop=req.stop,
                    ignore_eos=req.ignore_eos,
                    # each sampled copy diverges via the engine's
                    # SECOND fold level (seed_stream = copy index), so
                    # "seed s copy 1" never aliases "seed s+1 copy 0";
                    # copy-varying args are the one exception to the
                    # identical-args-per-copy rule the except clause
                    # below leans on (the engine validates neither)
                    seed=req.seed, seed_stream=req.admitted,
                    logprobs=req.logprobs,
                    # the records are deterministic and identical per
                    # copy: only copy 0 pays the full-prefill cost
                    # (copies 1..n-1 keep their APC tail-only prefill)
                    prompt_logprobs=(req.prompt_logprobs
                                     if req.admitted == 0 else None),
                    logit_bias=req.logit_bias,
                    min_tokens=req.min_tokens,
                    grammar=gid,
                    # the store vouched for the donor: only a
                    # warm-promoted session may match its own parked
                    # record (a cold pass must re-prefill, not half-
                    # trust whatever is resident)
                    session=(req.session if req.session_tier
                             else None))
            except PagePoolExhausted:
                # page pressure, not a bad request: demote an idle
                # parked session first (cheapest pages in the pool),
                # then preempt a STRICTLY lower-priority running copy
                # and retry this one (re-entering via _head keeps its
                # heap position); nothing yieldable means the pool is
                # honestly full — the request waits its turn
                if (self._session_store is not None
                        and self._session_store.demote_for_pages(
                            time.monotonic())):
                    self._head = req
                    continue
                if (min((r.priority for r, _ in
                         self._running.values()), default=req.priority)
                        < req.priority and self._preempt_for_pages()):
                    self._head = req
                    continue
                with self._lock:
                    self._pending_seq += 1
                    heapq.heappush(
                        self._pending,
                        (-req.priority, req._vft,
                         self._pending_seq, req))
                return None
            except (ValueError, RuntimeError) as e:
                # identical args per copy, so only the FIRST begin can
                # fail on validation (the scheduler pulls only with a
                # free slot, ruling out engine-full) — no
                # partially-errored requests
                self._requests_rejected += 1
                self._push(req, {"error": str(e), "code": 400})
                self._finish_request(req, "rejected")
                continue
            idx = req.admitted
            req.admitted += 1
            req.emitted[idx] = 0
            self._tickets[ticket] = (req, idx)
            if req.admitted < req.n:
                self._head = req  # the next pull continues this req
            return ticket

    def _ensure_grammar(self, req: _Request) -> int:
        """Engine-side grammar registration for *req*'s pattern
        (scheduler thread — the engine's sole owner); the gid cache
        makes it once-per-pattern, so the steady state is a dict
        lookup."""
        with self._glock:
            gid = self._grammar_gids.get(req.grammar_key)
        if gid is None:
            gid = self.engine.register_grammar(req.grammar_tdfa)
            with self._glock:
                # one critical section for the registered/pending
                # handoff: handler threads read BOTH maps for the
                # max_grammars bound and the compile-skip check, so
                # the insert and the pop must land atomically
                # (ADVICE r5).  Dropping the standalone TokenDfa
                # matters too: keeping it would pin a second full
                # [N, V] host copy per pattern for the server's
                # lifetime
                self._grammar_gids[req.grammar_key] = gid
                self._grammar_tdfas.pop(req.grammar_key, None)
        req.grammar_tdfa = None  # registered; drop the ref
        return gid

    def _push(self, req: _Request, ev) -> bool:
        """Queue *ev* for *req*'s connection without ever blocking the
        scheduler.  Event queues are BOUNDED (slow-client protection):
        a full queue means the client stopped draining, and the
        documented policy is disconnect, not unbounded buffering — the
        request is cancelled (the scheduler sweep releases its slots),
        the oldest undelivered event is dropped to make room, and a
        terminal 503 lands so a handler blocked in ``events.get()``
        wakes up and closes the connection."""
        try:
            req.events.put_nowait(ev)
            return True
        except queue.Full:
            if not req.dropped:
                req.dropped = True
                req.cancelled = True
                self._m_dropped.inc()
                self.recorder.record("tpu_serve_slow_client_drop",
                                     trace=req.trace, rid=req.rid)
                self._finish_request(req, "dropped")
                try:
                    req.events.get_nowait()
                except queue.Empty:
                    pass
                try:
                    req.events.put_nowait({
                        "error": "client not draining its stream; "
                                 "disconnecting (slow-client policy)",
                        "code": 503})
                except queue.Full:
                    pass
            return False

    def _emit(self, slot: int, req: _Request, idx: int,
              tokens: List[int]) -> None:
        """Push copy *idx*'s unseen tokens, honoring the budget and
        retiring the slot when the copy is done; the request completes
        when ALL n copies have.  The hot path coalesces each run_scan
        window's tokens into ONE pre-serialized JSON-lines frame
        (``{"tokens": [...]}``) — no per-token dict, dumps, or queue
        round-trip; ``per_token`` (and logprobs, whose stats are
        per-token) fall back to the legacy ``{"token": t}`` events.
        With a tokenizer, stop STRINGS are matched against the
        detokenized stream (a match truncates the copy there) and
        "text" deltas ride alongside the token frames, holding back
        any tail that could still become a stop string."""
        eng = self.engine
        seen = req.emitted[idx]
        new = tokens[seen:req.max_new_tokens]
        if new and not req.ttft_observed and req.t_arrival:
            # first generated token of ANY copy: the TTFT the client
            # perceives (queue wait + prefill + first window); the
            # trace-id rides along as the bucket's OpenMetrics exemplar
            req.ttft_observed = True
            ttft_dt = time.perf_counter() - req.t_arrival
            req.ttft_s = ttft_dt  # the SLO verdict reads this back
            self._m_ttft.observe(
                ttft_dt,
                trace_id=(req.trace.trace_id if req.trace else None))
            self._mark(req, "tpu_serve_ttft", ttft_dt)
        st = None
        if (req.stop_strs or req.detokenize) and self.tokenizer:
            st = req.detok.setdefault(idx, _DetokState())
            st.feed(self.tokenizer, tokens, min(len(tokens),
                                                req.max_new_tokens))
        stop_text = None  # truncated text when a stop string matched
        stop_keep = None  # tokens kept by the match (<= seen possible)
        if req.stop_strs and new:
            # min_tokens floors stop strings too (vLLM: no stop check
            # below the floor): scanning starts only past the floor, so
            # a match can only complete at token min_tokens+1 or later
            keep = scanned = None
            if seen + len(new) > req.min_tokens:
                scanned = True
                start = req.stop_scanned.get(idx, 0)
                while True:
                    keep, text = _find_stop(st, req.stop_strs, start)
                    if keep is None or keep > req.min_tokens:
                        break
                    # a match COMPLETING at or below the floor never
                    # fires (vLLM: no stop check below min_tokens) —
                    # resume scanning past its completion instead of
                    # clamping the cut to the floor, which used to
                    # leave the ids surface at min_tokens+1 while the
                    # text was cut at the (pre-floor) match start
                    start = st.cum[keep]
            if keep is not None:
                # kept tokens include the completing token; keep may
                # sit BELOW tokens already streamed (a detok stall or
                # floor-deferred scan) — the final tokens array
                # truncates to the kept count either way, so the ids
                # and text surfaces of one response always agree
                # (ADVICE r5; streamed frames past the match cannot be
                # unsent, the final array is authoritative)
                new = tokens[seen:keep] if keep > seen else []
                stop_text = text
                stop_keep = keep
            elif scanned:
                # resume point advances ONLY past text a scan actually
                # covered — below the floor nothing was scanned, and a
                # match there must still surface at the first
                # post-floor scan
                req.stop_scanned[idx] = len(st.text)
        lps = (eng.token_logprobs(slot) if req.logprobs else None)
        if new and req.stream and not req.openai:
            # OpenAI streams carry text deltas only (raw ids never hit
            # that wire); non-streaming requests need just the final
            # event — neither pays for token frames
            if lps is not None or req.per_token:
                # legacy per-token shape (and logprobs, whose stats
                # are inherently per-token)
                for j, t in enumerate(new):
                    ev = {"token": int(t)}
                    if req.n > 1:
                        ev["index"] = idx
                    if lps is not None:
                        clp, top = lps[seen + j]
                        ev["logprob"] = clp
                        ev["top_logprobs"] = [[i, p] for i, p in top]
                    if not self._push(req, ev):
                        break
            else:
                # engine-rate hot path: the whole window in one
                # pre-encoded frame, one queue hop, one client write
                self._push(req, _tokens_frame(new, idx, req.n))
        req.emitted[idx] = seen + len(new)
        finished = eng.finished(slot)
        done = (stop_text is not None
                or req.emitted[idx] >= req.max_new_tokens or finished)
        if req.detokenize and req.stream:
            # the committed incremental text (never ends mid-char:
            # _DetokState withholds UTF-8-unstable tails, so the old
            # U+FFFD backscan is structurally unnecessary), capped at
            # the emitted token count; a stop match overrides with its
            # truncation.  An eos finish excludes the eos token from
            # the TEXT (OpenAI/vLLM semantics: special tokens never
            # reach text; the ids surface keeps it)
            n_text = req.emitted[idx]
            if (stop_text is None and finished and n_text
                    and eng.finish_reason(slot) == "eos"
                    and int(tokens[n_text - 1]) == eng.eos_id):
                n_text -= 1
            cur = (stop_text if stop_text is not None
                   else st.text[:st.cum[n_text]])
            hold = (0 if done or not req.stop_strs
                    else _holdback(cur, req.stop_strs))
            safe = len(cur) - hold
            # if an earlier emission turns out to mismatch (a stop
            # truncation rewrote history), stop emitting deltas; the
            # final event carries the authoritative full text
            sent = req.text_sent.get(idx, "")
            if cur[:len(sent)] == sent and safe > len(sent):
                ev = {"text": cur[len(sent):safe]}
                if req.n > 1:
                    ev["index"] = idx
                self._push(req, ev)
                req.text_sent[idx] = cur[:safe]
        if req.cancelled:
            eng.release(slot)
            del self._running[slot]
            return
        if done:
            if stop_text is not None:
                out = tokens[:stop_keep]
                reason = "stop"
            else:
                full = eng.output(slot)
                out = full[:req.max_new_tokens]
                if finished and len(full) <= req.max_new_tokens:
                    # the engine's own verdict (eos / stop / length)
                    reason = eng.finish_reason(slot) or "length"
                else:
                    # budget cut the stream before (or at) the
                    # engine's retirement point
                    reason = "length"
            # session tiering: a conversation's retiring slot parks as
            # its device tier (KV pages + record stay, slot reserved)
            # instead of releasing — the next turn warm-resumes.
            # Parking reads the slot's live lens/outputs, so it must
            # happen HERE, before any release resets them; logprob
            # records survive the park exactly as they survive a
            # release.
            if not self._park_session(req, slot, len(out)) \
                    and not finished:
                eng.release(slot)
            choice = {
                "index": idx,
                "tokens": [int(t) for t in out],
                "finish_reason": reason,
            }
            if req.detokenize:
                text_ids = [int(t) for t in out]
                if (stop_text is None and reason == "eos" and text_ids
                        and text_ids[-1] == eng.eos_id):
                    # eos is data on the ids surface, never text
                    text_ids = text_ids[:-1]
                choice["text"] = (
                    stop_text if stop_text is not None
                    else self.tokenizer.decode(text_ids))
            if req.logprobs:
                choice["logprobs"] = [
                    {"logprob": clp,
                     "top_logprobs": [[i, p] for i, p in top]}
                    for clp, top in
                    eng.token_logprobs(slot)[:len(out)]
                ]
            if req.prompt_logprobs and idx == 0:
                choice["prompt_logprobs"] = [
                    None if rec is None else
                    {"logprob": rec[0],
                     "top_logprobs": [[i, p] for i, p in rec[1]]}
                    for rec in eng.prompt_logprobs(slot)
                ]
            del self._running[slot]
            req.choices.append(choice)
            if len(req.choices) == req.n:
                if req.n == 1:
                    done = {"done": True, **req.choices[0]}
                    del done["index"]  # single-completion wire shape
                else:
                    done = {"done": True, "choices": sorted(
                        req.choices, key=lambda c: c["index"])}
                    if req.prompt_logprobs:
                        # identical across copies — attached ONCE,
                        # from the one copy that computed them
                        for ch in done["choices"]:
                            if "prompt_logprobs" in ch:
                                done["prompt_logprobs"] = ch.pop(
                                    "prompt_logprobs")
                # count BEFORE the event lands: a client reacting to
                # the final chunk must not read a stale /stats counter
                self._requests_served += 1
                self._push(req, done)
                self._finish_request(req, "ok")

    def _park_session(self, req: "_Request", slot: int,
                      kept: int) -> bool:
        """Park the retiring slot as *req*'s session device tier.
        Returns False — caller releases as before — whenever tiering
        is off, inapplicable (multi-copy, dropped client, prefill
        side), or the park fails; parking is strictly best-effort."""
        store = self._session_store
        if (store is None or not req.session or req.n != 1
                or req.dropped or req.prefill_only or req.cancelled):
            return False
        try:
            canon = self.engine.park_session(slot, req.session, kept)
        except Exception as e:
            suppressed("server.park_session", e, log)
            return False
        now_s = time.monotonic()
        store.note_parked(req.session, slot, now_s)
        self.recorder.record(
            "tpu_kv_park", trace=req.trace, rid=req.rid, slot=slot,
            session=sid_hash(req.session), canon=canon)
        return True

    def _scheduler_loop(self) -> None:
        eng = self.engine
        sched = self._sched
        while not self._stop.is_set():
            # drop requests whose client went away: running slots and
            # admissions still prefilling alike
            for slot, (req, _idx) in list(self._running.items()):
                if req.cancelled:
                    eng.release(slot)
                    del self._running[slot]
            for ticket, (req, _idx) in list(self._tickets.items()):
                if req.cancelled:
                    sched.cancel(ticket)
                    del self._tickets[ticket]
            if self._session_store is not None:
                # tiering policy tick (engine ops are scheduler-thread
                # only): idle demotions, host-cap/disk GC, handler
                # export requests, and — when admissions are waiting —
                # slot-pressure demotion of parked sessions
                self._session_store.tick(
                    time.monotonic(),
                    slot_pressure=self._intake_waiting())
            if (not self._running and not sched.busy()
                    and not self._intake_waiting()):
                # idle: wait for work without spinning (admission is
                # priority-then-FIFO; requests stay in the heap).  The
                # wait is the loop's "idle" phase — the denominator of
                # the device duty-cycle gauge
                t_idle = time.perf_counter()
                sched.begin_phase("idle")
                self._work.wait(timeout=_IDLE_POLL_S)
                self._work.clear()
                sched.note_phase("idle",
                                 time.perf_counter() - t_idle)
                continue
            # chaos hooks (serve.step / serve.schedule) fire INSIDE
            # iterate, after admission work and before the decode
            # round — an armed fault can never crash an idle loop, and
            # a crashed iteration's requests are already ticket-bound
            # so the supervisor's drain 503s every one of them
            t_win = time.perf_counter()
            # one scheduler iteration: admission work (pull, prefill
            # chunks, finishes) interleaved with at most one decode
            # round — scan window, spec round, jump round, or endgame
            # step (the scheduler replicates the old adaptive choice)
            if self._sched_watchdog is not None:
                res = self._sched_watchdog.call(sched.iterate)
            else:
                res = sched.iterate()
            win_dt = time.perf_counter() - t_win
            # admissions were bound + their first tokens emitted the
            # moment they resolved (the scheduler's on_admit callback
            # fires mid-window); only decode output is left to stream
            if not res.steps:
                continue
            t_stream = time.perf_counter()
            sched.begin_phase("stream")
            for slot, (req, idx) in list(self._running.items()):
                before = req.emitted.get(idx, 0)
                self._emit(slot, req, idx, eng.output(slot))
                k = req.emitted.get(idx, 0) - before
                if k > 0:
                    # the stream's inter-token latency this window:
                    # window wall time spread over its k tokens,
                    # weighted by token count (one bulk observe)
                    self._m_token.observe_n(win_dt / k, k)
                    self._mark(req, "tpu_serve_window", win_dt,
                               tokens=k, slot=slot)
            # the post-harvest emit work is the loop's "stream" phase:
            # with --overlap-dispatch the next window is already on
            # the device underneath it (that is the overlap's win)
            sched.note_phase("stream",
                             time.perf_counter() - t_stream)
        # the scheduler owns _running/_head: it performs the shutdown
        # drain itself so stop() never mutates them while a device step
        # is still in flight (a stuck 5s join used to race here)
        if self._session_store is not None:
            # a clean shutdown pushes every parked conversation to the
            # disk tier: the respawned generation rehydrates them
            # lazily on first touch
            self._session_store.spill_all(time.monotonic())
        self._drain_on_stop()

    def _intake_waiting(self) -> bool:
        """Anything in the priority heap (or a partially-admitted n>1
        head) the scheduler could pull?"""
        with self._lock:
            return bool(self._pending) or self._head is not None

    def _budget_hint(self, slot: int):
        """Remaining-token hint for the scheduler's adaptive window:
        how many more steps this slot's request needs.  None (= stay
        at the window floor) for stop-STRING requests — their cut is
        a server-side text scan, so harvest granularity is the only
        thing bounding post-stop garbage decode."""
        binding = self._running.get(slot)
        if binding is None:
            return None
        req, idx = binding
        if req.stop_strs:
            return None
        return max(1, req.max_new_tokens - req.emitted.get(idx, 0))

    def _bind_admitted(self, ticket) -> None:
        """An admission went live (the scheduler's on_admit callback,
        possibly MID-WINDOW): bind the slot into ``_running`` and
        stream the admission's first sampled token right away."""
        eng = self.engine
        binding = self._tickets.pop(ticket, None)
        if binding is None:
            # cancelled after its splice landed: free the slot
            eng.release(ticket.slot)
            return
        req, idx = binding
        admit_dt = ticket.t_done - ticket.t_begin
        self._m_admit.observe(admit_dt)
        self._mark(req, "tpu_serve_admit", admit_dt,
                   slot=ticket.slot, copy=idx,
                   chunks=ticket.chunks_total,
                   mid_window=ticket.mid_window)
        if (req.prefill_only and not req.cancelled
                and not eng.finished(ticket.slot)
                and req.max_new_tokens > 1):
            # disaggregated prefill: the admission (packed prefill +
            # first token) is exactly the work this replica class
            # exists for — checkpoint the fresh slot bit-exactly to
            # host, free its pages, and hand the state to the handler
            # thread, which answers the router with the serialized
            # payload instead of a token stream.  A request that
            # already FINISHED at its first token (eos/stop, or a
            # 1-token budget) has nothing left to migrate: it falls
            # through and this replica serves the complete response
            # itself (the router passes it straight through).
            self._export_migration(req, ticket.slot)
            return
        self._running[ticket.slot] = (req, idx)
        self._emit(ticket.slot, req, idx, eng.output(ticket.slot))

    def _export_migration(self, req: _Request, slot: int) -> None:
        """Checkpoint a freshly-admitted prefill-only slot and hand
        the state to the request's handler thread (scheduler thread —
        preempt is an engine call).  Grammar state is re-based to
        grammar-LOCAL form so the decode replica can re-home it onto
        its own combined table regardless of registration order."""
        eng = self.engine
        try:
            state = eng.preempt(slot)
        except (RuntimeError, ValueError) as e:
            # cannot checkpoint (should not happen on a paged engine
            # with an active slot): serve the request here instead of
            # failing it — correctness over topology
            log.warning("prefill-only export failed (%s); serving "
                        "locally", e)
            self.recorder.record("tpu_serve_migrate_declined",
                                 trace=req.trace, rid=req.rid,
                                 error=str(e))
            self._running[slot] = (req, 0)
            self._emit(slot, req, 0, eng.output(slot))
            return
        if req.grammar_key is not None:
            state["gstate"] = eng.grammar_rel(int(state["gstate"]))
            state["gstate_rel"] = True
        self._mig_out.inc()
        self.recorder.record("tpu_serve_migrate_out",
                             trace=req.trace, rid=req.rid, slot=slot,
                             tokens=len(req.tokens),
                             outputs=len(state["outputs"]))
        self._push(req, {"__migrate__": state})
        self._finish_request(req, "migrated")

    def _admit_pending(self) -> None:
        """Synchronously admit every queued request copy that fits —
        the pre-scheduler cadence, kept as the deterministic hook for
        tests and embedders that drive the engine without the loop
        thread (the loop itself admits through ``iterate()``, where
        prefill interleaves with open decode windows).  Binding and
        first-token emission ride the scheduler's on_admit callback."""
        self._sched._drain_admissions()

    def warm_scheduler(self) -> None:
        """Pre-compile the scheduler's quantized adaptive-window scan
        variants AND the ragged packed-prefill shape set.  Every
        distinct window length — and every pack size's [K, chunk]
        extend — is its own XLA compile; without this, the FIRST
        synchronized batch (or packed convoy) eats seconds of compile
        mid-traffic.  The CLI and the serving bench call it before
        taking traffic; tests that never hit grown windows skip the
        cost.  Call BEFORE start() or while idle — it drives the
        engine directly.

        Observes ``tpu_serve_warmup_seconds{phase}`` so replica
        cold-start cost is a dashboard number; with a warm
        ``--compile-cache-dir`` the phases collapse to cache loads
        (the cold-start bench asserts the delta)."""
        eng = self.engine
        t_start = time.perf_counter()
        slot = eng.admit([0], ignore_eos=True)
        try:
            for k in range(1, ADAPTIVE_WINDOW_FACTOR + 1):
                n = self.window * k
                if eng.lens[slot] + n > eng.model.max_len:
                    break
                eng.run_scan(n)
        finally:
            eng.release(slot)
        t_scan = time.perf_counter()
        self._m_warmup.labels(phase="scan").set(t_scan - t_start)
        if self._sched._packing:
            # only when the scheduler can actually pack (chunked
            # engine, no MoE): a shape the packed path never
            # dispatches is compile time for nothing
            eng.warm_packed(
                range(2, self._sched.max_pack + 1))
            self._m_warmup.labels(phase="packed_prefill").set(
                time.perf_counter() - t_scan)
        self._m_warmup.labels(phase="total").set(
            time.perf_counter() - t_start)

    def _scheduler_supervisor(self) -> None:
        """Crash containment for the engine's sole owner.  A scheduler
        crash used to be a silent hang: the thread died, every
        connected client blocked forever on its event queue, and
        /healthz kept answering ok.  Now each crash 503s the in-flight
        requests (their slots released) and restarts the loop with
        capped backoff; a crash LOOP (``_SCHED_MAX_RESTARTS`` in a row
        without ``_SCHED_CRASH_RESET_S`` of clean running) marks the
        server dead — new requests get an immediate 503 and /healthz
        fails so the orchestrator replaces the pod."""
        crashes = 0
        last_crash = 0.0
        while not self._stop.is_set():
            try:
                self._scheduler_loop()
                return  # clean stop-path exit; loop already drained
            except Exception as e:
                now = time.monotonic()
                crashes = (1 if now - last_crash > _SCHED_CRASH_RESET_S
                           else crashes + 1)
                last_crash = now
                log.exception("engine scheduler crashed (%d/%d)",
                              crashes, _SCHED_MAX_RESTARTS)
                self._m_sched_crashes.inc()
                self.recorder.record(
                    "tpu_serve_scheduler_crash",
                    error=f"{type(e).__name__}: {e}", crashes=crashes)
                # invalidate the crashed iteration FIRST: a
                # watchdog-abandoned worker that wakes later re-checks
                # the generation and bails before touching the engine
                # the restarted loop now owns; pending admissions are
                # aborted (their requests 503 in the drain below)
                try:
                    self._sched.supersede()
                except Exception as se:
                    log.debug("post-crash scheduler supersede "
                              "failed: %s", se)
                # contain: free every engine slot (their device state
                # is suspect after an arbitrary crash point) and 503
                # the requests that were riding them
                for slot in list(self._running):
                    try:
                        self.engine.release(slot)
                    except Exception as re:
                        log.debug("post-crash release of slot %s "
                                  "failed: %s", slot, re)
                self._drain_on_stop(
                    "engine scheduler crashed; request aborted — "
                    "retry")
                if crashes >= _SCHED_MAX_RESTARTS:
                    break
                self._m_sched_restarts.inc()
                self.recorder.record("tpu_serve_scheduler_restart",
                                     attempt=crashes)
                if self._stop.wait(min(0.05 * (2 ** (crashes - 1)),
                                       _SCHED_BACKOFF_MAX_S)):
                    return
        if self._stop.is_set():
            return
        # permanent death: drain the pending heap too and refuse new
        # work at admission (see _enqueue) and /healthz
        self._sched_dead = True
        self.recorder.record("tpu_serve_scheduler_dead",
                             crashes=crashes)
        log.error("engine scheduler dead after %d consecutive "
                  "crashes; serving 503s until restarted", crashes)
        bye = {"error": "engine scheduler crashed; server needs a "
                        "restart", "code": 503}
        with self._lock:
            drained, self._pending = self._pending, []
        for *_k, req in drained:
            self._push(req, dict(bye))
            self._finish_request(req, "shutdown")

    def _drain_on_stop(self, reason: str = "server shutting down"
                       ) -> None:
        """Send every connected client a terminal 503. Idempotent."""
        bye = {"error": reason, "code": 503}
        try:
            self._sched.supersede()  # abort in-flight admissions
        except Exception as se:
            log.debug("drain-time scheduler supersede failed: %s", se)
        notified = set()
        for req, _idx in self._running.values():
            if id(req) not in notified:
                notified.add(id(req))
                self._push(req, dict(bye))
                self._finish_request(req, "shutdown")
        self._running.clear()
        # admissions still prefilling when the loop died: same 503
        # (their tickets were aborted by supersede/stop — the slot
        # reservation is gone either way)
        for req, _idx in self._tickets.values():
            if id(req) not in notified:
                notified.add(id(req))
                self._push(req, dict(bye))
                self._finish_request(req, "shutdown")
        self._tickets.clear()
        if self._head is not None:
            if id(self._head) not in notified:
                self._push(self._head, dict(bye))
                self._finish_request(self._head, "shutdown")
            self._head = None

    # -- lifecycle ----------------------------------------------------------

    def start(self, host: str = "0.0.0.0", port: int = 8000
              ) -> "EngineServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # per-connection socket deadline: a peer that stops
            # reading AND writing cannot pin a pool worker forever
            timeout = server.client_timeout

            def do_GET(self):  # noqa: N802 (http.server API)
                self._trace = None  # keep-alive: no stale echo
                url = urlparse(self.path)
                if url.path == "/healthz":
                    if server.healthy():
                        self._send(200, "text/plain", "ok\n")
                    else:
                        # a dead scheduler must flunk the liveness
                        # probe, not keep the pod looking fine while
                        # every request 503s
                        self._send(503, "text/plain",
                                   "engine scheduler dead\n")
                elif url.path == "/stats":
                    body = json.dumps(server.stats(), indent=2)
                    self._send(200, "application/json", body + "\n")
                elif url.path == "/statz":
                    # the router's load-signal poll: small, flat, and
                    # in lock-step with the /metrics families (see
                    # statz()); kept off /stats so the router never
                    # pays for the full engine dump
                    self._send(200, "application/json",
                               json.dumps(server.statz()) + "\n")
                elif url.path == "/metrics":
                    # Prometheus exposition (vLLM's server exposes
                    # /metrics; scrape configs expect it from a
                    # serving pod): the obs registry — request/TTFT/
                    # per-token histograms, shed counters — plus the
                    # bridged engine stats.  The OpenMetrics Accept
                    # type additionally gets trace-id exemplars + EOF;
                    # the plain exposition is byte-compatible with
                    # pre-exemplar scrapes
                    om = obs.negotiate_openmetrics(
                        self.headers.get("Accept"))
                    try:
                        body = server.render_metrics(openmetrics=om)
                    except Exception:
                        log.exception("/metrics render failed")
                        self._send(500, "text/plain",
                                   "internal error; see server logs\n")
                        return
                    self._send(
                        200,
                        obs.OPENMETRICS_CONTENT_TYPE if om
                        else obs.TEXT_CONTENT_TYPE,
                        body)
                elif url.path == "/alerts":
                    # alert-evaluator surface (PR 18): every rule's
                    # state machine + the firing roll-up, same schema
                    # on all four HTTP surfaces
                    self._send(200, "application/json",
                               server.alerts.status_json() + "\n")
                elif url.path == "/debug/query":
                    # retained-series readback: ?expr=&range= against
                    # the in-process TSDB (rate()/increase()/
                    # avg_over_time()/histogram_quantile over the ring
                    # buffers the background tick fills)
                    params = {k: v[0] for k, v
                              in parse_qs(url.query).items()}
                    try:
                        body_s = server.tsdb.handle_query_json(params)
                    except ValueError as e:
                        self._send(400, "application/json", json.dumps(
                            {"error": str(e)}) + "\n")
                        return
                    self._send(200, "application/json", body_s + "\n")
                elif url.path == "/debug/traces":
                    # ?trace_id=… -> that trace's event timeline;
                    # without it, the recent-trace index
                    q = parse_qs(url.query)
                    tid = q.get("trace_id", [None])[0]
                    if tid:
                        body = {"trace_id": tid,
                                "events": server.recorder.events(
                                    trace_id=tid)}
                    else:
                        body = {"traces": server.recorder.trace_ids()}
                    self._send(200, "application/json",
                               json.dumps(body, indent=2) + "\n")
                elif url.path == "/debug/events":
                    # ?since=<wall seconds> -> events after that stamp
                    q = parse_qs(url.query)
                    try:
                        since = float(q.get("since", ["0"])[0])
                    except ValueError:
                        self._send(400, "application/json", json.dumps(
                            {"error": "'since' must be a unix "
                                      "timestamp"}) + "\n")
                        return
                    body = {"since": since,
                            "dropped": server.recorder.dropped,
                            "events": server.recorder.events(
                                since=since)}
                    self._send(200, "application/json",
                               json.dumps(body, indent=2) + "\n")
                elif url.path == "/debug/profile":
                    # continuous-profiling hook: capture ?seconds=N of
                    # jax.profiler trace into --profile-dir.  Blocking
                    # (the worker sleeps through the capture), single-
                    # flight (concurrent capture answers 409)
                    q = parse_qs(url.query)
                    try:
                        seconds = float(q.get("seconds", ["1"])[0])
                    except ValueError:
                        self._send(400, "application/json", json.dumps(
                            {"error": "'seconds' must be a number"})
                            + "\n")
                        return
                    try:
                        out = server.profile(seconds)
                    except ValueError as e:
                        self._send(400, "application/json",
                                   json.dumps({"error": str(e)}) + "\n")
                        return
                    except RuntimeError as e:
                        self._send(409, "application/json",
                                   json.dumps({"error": str(e)}) + "\n")
                        return
                    except Exception as e:
                        log.exception("/debug/profile capture failed")
                        self._send(500, "application/json", json.dumps(
                            {"error": f"profiler failed: {e}"}) + "\n")
                        return
                    self._send(200, "application/json",
                               json.dumps(out) + "\n")
                elif url.path == "/debug/pprof":
                    # the always-on sampling profiler's ring (PR 19):
                    # ?seconds=N&format=folded|json — folded stacks
                    # pipe straight into flamegraph.pl / speedscope
                    try:
                        ctype, body = server.profiler.handle_pprof(
                            parse_qs(url.query))
                    except ValueError as e:
                        self._send(400, "application/json",
                                   json.dumps({"error": str(e)}) + "\n")
                        return
                    self._send(200, ctype, body)
                else:
                    self._send(404, "text/plain", "not found\n")

            def do_POST(self):  # noqa: N802
                # trace intake: continue the caller's traceparent as a
                # child context, or open a fresh root (malformed
                # headers fall back, never reject); every response
                # path echoes the trace-id back (see _send)
                self._trace = obs.trace_from_header(
                    self.headers.get("traceparent"))
                if self.path == "/v1/completions":
                    self._openai_completions(chat=False)
                    return
                if self.path == "/v1/chat/completions":
                    self._openai_completions(chat=True)
                    return
                if self.path == "/migrate":
                    self._migrate()
                    return
                if self.path == "/session/export":
                    self._session_export()
                    return
                if self.path == "/session/import":
                    self._session_import()
                    return
                if self.path != "/generate":
                    self._send(404, "text/plain", "not found\n")
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length))
                except (ValueError, TypeError) as e:
                    self._send(400, "application/json",
                               json.dumps({"error": str(e)}) + "\n")
                    return
                self._generate(body)

            def _generate(self, body, migrate_state=None,
                          migrate_budget=None):
                """The native /generate path; also the resume half of
                /migrate (a checkpoint rides in as *migrate_state*
                with the prefill replica's capped *migrate_budget*)."""
                try:
                    req = server._parse_request(body,
                                                trace=self._trace)
                    if migrate_state is not None:
                        server._attach_migration(req, migrate_state,
                                                 migrate_budget)
                except (ValueError, TypeError, KeyError) as e:
                    self._send(400, "application/json",
                               json.dumps({"error": str(e)}) + "\n")
                    return
                server._enqueue(req)
                try:
                    if req.prefill_only:
                        self._migrate_reply(req, body, "/generate")
                    elif req.stream:
                        self._stream(req)
                    else:
                        self._collect(req)
                except (BrokenPipeError, ConnectionResetError,
                        TimeoutError):
                    req.cancelled = True
                    server._note_client_abandon(req)
                    server._finish_request(req, "cancelled")

            def _migrate(self):
                """POST /migrate (internal, replica-to-replica via the
                router): resume a prefill replica's checkpoint into a
                slot here and serve the request's stream from where
                prefill left off."""
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                try:
                    payload = load_payload(raw)
                    path = payload["path"]
                    body = payload["body"]
                    state = payload["state"]
                    budget = int(payload["budget"])
                    if path not in ("/generate", "/v1/completions",
                                    "/v1/chat/completions"):
                        raise MigrateError(f"bad path {path!r}")
                    if not isinstance(body, dict) \
                            or not isinstance(state, dict):
                        raise MigrateError(
                            "body and state must be objects")
                except (MigrateError, KeyError, TypeError,
                        ValueError) as e:
                    self._send(400, "application/json", json.dumps(
                        {"error": f"bad migration payload: {e}"})
                        + "\n")
                    return
                pool = getattr(server.engine, "_pool", None)
                if not getattr(server.engine, "kv_paging", False) \
                        or pool is None:
                    # a replica without a paged pool cannot resume a
                    # checkpoint: 503 so the router retries elsewhere
                    self._send(503, "application/json", json.dumps(
                        {"error": "replica cannot resume migrated KV "
                                  "state (no paged pool)",
                         "code": 503}) + "\n")
                    return
                lens = int(state.get("lens", 0))
                if lens < 1 or lens > server.engine.model.max_len \
                        or pool.pages_needed(lens) > pool.n_pages:
                    self._send(503, "application/json", json.dumps(
                        {"error": f"checkpoint of {lens} tokens does "
                                  "not fit this replica's pool",
                         "code": 503}) + "\n")
                    return
                if path == "/generate":
                    self._generate(body, migrate_state=state,
                                   migrate_budget=budget)
                else:
                    self._openai_completions(
                        chat=path.endswith("/chat/completions"),
                        body=body, migrate_state=state,
                        migrate_budget=budget)

            def _session_export(self):
                """POST /session/export (internal, router-driven):
                hand a parked session's checkpoint to the replica the
                router now routes the session to (single-owner move —
                the local copy is dropped on success)."""
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length))
                    sid = str(body.get("session_id") or "")
                    if not sid:
                        raise ValueError("session_id required")
                except (ValueError, TypeError) as e:
                    self._send(400, "application/json",
                               json.dumps({"error": str(e)}) + "\n")
                    return
                store = server._session_store
                if store is None:
                    self._send(503, "application/json", json.dumps(
                        {"error": "session tiering disabled",
                         "code": 503}) + "\n")
                    return
                try:
                    payload = store.export_session(sid)
                except KeyError:
                    self._send(404, "application/json", json.dumps(
                        {"error": "unknown session"}) + "\n")
                    return
                except Exception as e:
                    log.warning("session export %s failed: %s", sid, e)
                    self._send(503, "application/json", json.dumps(
                        {"error": f"session export failed: {e}",
                         "code": 503}) + "\n")
                    return
                self._send_bytes(200, MIGRATE_CONTENT_TYPE, payload)

            def _session_import(self):
                """POST /session/import (internal, router-driven):
                accept another replica's session checkpoint into the
                host tier; the session's first request here promotes
                it to device."""
                store = server._session_store
                if store is None:
                    self._send(503, "application/json", json.dumps(
                        {"error": "session tiering disabled",
                         "code": 503}) + "\n")
                    return
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                try:
                    sid = store.import_payload(raw, time.monotonic())
                except (MigrateError, ValueError, TypeError) as e:
                    self._send(400, "application/json", json.dumps(
                        {"error": f"bad session payload: {e}"}) + "\n")
                    return
                self._send(200, "application/json", json.dumps(
                    {"ok": True, "session": sid_hash(sid)}) + "\n")

            def _migrate_reply(self, req: _Request, body, path,
                               openai=False, model_name=None,
                               chat=False):
                """Answer a prefill_only request: the serialized
                checkpoint payload (the router ships it to a decode
                replica) — or, when the scheduler declined (the
                request FINISHED at its first token), the normal
                response the client expects anyway."""
                first = req.events.get()
                if isinstance(first, dict) and "error" in first:
                    if openai:
                        self._openai_error(first.get("code", 400),
                                           first["error"])
                    else:
                        self._send(first.get("code", 400),
                                   "application/json",
                                   json.dumps(first) + "\n")
                    return
                if isinstance(first, dict) and "__migrate__" in first:
                    payload = dump_payload({
                        "path": path,
                        "body": {k: v for k, v in body.items()
                                 if k != "prefill_only"},
                        "state": first["__migrate__"],
                        # the budget as THIS replica capped it (prompt
                        # + budget must fit max_len) — the decode
                        # replica adopts it instead of re-deriving
                        "budget": req.max_new_tokens,
                    })
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     MIGRATE_CONTENT_TYPE)
                    self.send_header("Content-Length",
                                     str(len(payload)))
                    self._trace_headers()
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                # declined at bind (finished at the first token):
                # serve the normal response, starting from the event
                # already in hand
                if openai:
                    if req.stream:
                        self._openai_stream(req, model_name, chat,
                                            first=first)
                    else:
                        self._openai_collect(req, model_name, chat,
                                             first=first)
                elif req.stream:
                    self._stream(req, first=first)
                else:
                    self._collect(req, first=first)

            def _openai_completions(self, chat: bool = False,
                                    body=None, migrate_state=None,
                                    migrate_budget=None):
                """OpenAI-compatible text completions (the interface
                vLLM serves first): translate the body onto the native
                request, answer in the OpenAI wire shape — streamed as
                SSE `data:` chunks or one JSON object.  /migrate
                resumption rides in via *body* + *migrate_state*."""
                stream = False
                try:
                    if body is None:
                        length = int(
                            self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(length))
                    stream = bool(body.get("stream", False))
                    native, model_name = (
                        server._openai_chat_to_native(body) if chat
                        else server._openai_to_native(body))
                    if body.get("prefill_only"):
                        # the router's disagg marker rides through the
                        # OpenAI translation like any native field
                        native["prefill_only"] = True
                    if stream and native.get("logprobs") is not None:
                        # explicit 400 beats silently dropping the
                        # data: the SSE chunks carry text deltas that
                        # do not align 1:1 with tokens
                        raise ValueError(
                            "logprobs with stream=true is not "
                            "supported; request them unstreamed")
                    req = server._parse_request(native,
                                                trace=self._trace)
                    if migrate_state is not None:
                        server._attach_migration(req, migrate_state,
                                                 migrate_budget)
                    if native.get("_lp_count") is not None:
                        # the client-requested count (may be 0): the
                        # response trims the engine's top list to it
                        req.openai_logprobs = native["_lp_count"]
                    req.echo = bool(native.get("_echo"))
                    if req.echo:
                        # the ORIGINAL prompt string when the client
                        # sent one (decode(req.tokens) would echo the
                        # tokenizer's BOS/special text); token-array
                        # prompts decode skipping specials when the
                        # tokenizer supports it
                        if isinstance(native.get("prompt"), str):
                            req.echo_text = native["prompt"]
                        else:
                            try:
                                req.echo_text = server.tokenizer.decode(
                                    req.tokens,
                                    skip_special_tokens=True)
                            except TypeError:  # minimal test fakes
                                req.echo_text = server.tokenizer.decode(
                                    req.tokens)
                    req.include_usage = bool(
                        native.get("_include_usage"))
                except (ValueError, TypeError, KeyError) as e:
                    self._openai_error(400, str(e))
                    return
                req.openai = True   # text deltas only on this wire
                req.stream = stream
                server._enqueue(req)
                try:
                    if req.prefill_only:
                        self._migrate_reply(
                            req, body,
                            "/v1/chat/completions" if chat
                            else "/v1/completions",
                            openai=True, model_name=model_name,
                            chat=chat)
                    elif stream:
                        self._openai_stream(req, model_name, chat)
                    else:
                        self._openai_collect(req, model_name, chat)
                except (BrokenPipeError, ConnectionResetError,
                        TimeoutError):
                    req.cancelled = True
                    server._note_client_abandon(req)
                    server._finish_request(req, "cancelled")

            def _openai_error(self, code: int, message: str):
                """OpenAI error wire shape; 5xx are server faults so
                retry middleware retries them, 429 is rate limiting
                (with Retry-After), other 4xx are caller errors."""
                kind = ("server_error" if code >= 500
                        else "rate_limit_exceeded" if code == 429
                        else "invalid_request_error")
                self._send(code, "application/json",
                           json.dumps({"error": {
                               "message": message,
                               "type": kind}}) + "\n")

            def _openai_stream(self, req: _Request, model_name,
                   chat: bool = False, first=None):
                if first is None:
                    first = req.events.get()
                if "error" in first:
                    self._openai_error(first.get("code", 400),
                                       first["error"])
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                self._trace_headers()
                self.end_headers()
                # the completion id IS the trace id: a slow completion
                # pasted into /debug/traces resolves without any
                # id-to-id mapping step
                rid = f"cmpl-{req.trace.trace_id}"
                if chat:
                    # the chat stream contract: role arrives in the
                    # first chunk's delta, content in later deltas
                    self._chunk("data: " + json.dumps(_sse_envelope(
                        rid, model_name, True,
                        [{"index": i,
                          "delta": {"role": "assistant"},
                          "finish_reason": None}
                         for i in range(req.n)],
                        **({"usage": None} if req.include_usage
                           else {}))) + "\n\n")
                if req.echo and not chat:
                    # OpenAI echo streams the prompt text first, one
                    # chunk covering every choice (it never counts
                    # toward the completion's sent-text accounting)
                    self._chunk("data: " + json.dumps(_sse_envelope(
                        rid, model_name, False,
                        [{"index": i, "text": req.echo_text,
                          "finish_reason": None}
                         for i in range(req.n)],
                        **({"usage": None} if req.include_usage
                           else {}))) + "\n\n")
                sent: dict = {}  # index -> streamed text so far
                ev = first
                while True:
                    if "error" in ev:
                        # mid-stream failure (e.g. shutdown drain):
                        # surface it as an error chunk, never as a
                        # clean-looking [DONE]
                        kind = ("server_error"
                                if ev.get("code", 400) >= 500
                                else "invalid_request_error")
                        self._chunk("data: " + json.dumps({
                            "error": {"message": ev["error"],
                                      "type": kind}}) + "\n\n")
                        break
                    chunk = _openai_chunk(
                        rid, model_name, ev, sent, chat=chat,
                        include_usage=req.include_usage)
                    if chunk is not None:
                        self._chunk("data: " + json.dumps(chunk)
                                    + "\n\n")
                    if "done" in ev:
                        if req.include_usage:
                            # stream_options.include_usage: one final
                            # usage-only chunk before [DONE]
                            chs = (ev["choices"] if "choices" in ev
                                   else [ev])
                            completion = sum(
                                len(c.get("tokens", ()))
                                for c in chs)
                            self._chunk("data: " + json.dumps(
                                _sse_envelope(
                                    rid, model_name, chat, [],
                                    usage=_usage(len(req.tokens),
                                                 completion)))
                                + "\n\n")
                        break
                    ev = req.events.get()
                self._chunk("data: [DONE]\n\n")
                self._chunk("")

            def _openai_collect(self, req: _Request, model_name,
                    chat: bool = False, first=None):
                while True:
                    ev = first if first is not None \
                        else req.events.get()
                    first = None
                    if "error" in ev:
                        self._openai_error(ev.get("code", 400),
                                           ev["error"])
                        return
                    if "done" in ev:
                        echo_text = (req.echo_text if req.echo
                                     else None)
                        self._send(
                            200, "application/json",
                            json.dumps(_openai_response(
                                f"cmpl-{req.trace.trace_id}",
                                model_name, req, ev, chat=chat,
                                echo_text=echo_text)) + "\n")
                        return

            def _stream(self, req: _Request, first=None):
                # wait for the FIRST event before sending headers: an
                # admission-time rejection must surface as a real 4xx,
                # not an in-band error line on a 200 (status-checking
                # clients — curl -f, k8s probes — would see success)
                if first is None:
                    first = req.events.get()
                if isinstance(first, dict) and "error" in first:
                    self._send(first.get("code", 400),
                               "application/json",
                               json.dumps(first) + "\n")
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/jsonlines")
                self.send_header("Transfer-Encoding", "chunked")
                self._trace_headers()
                self.end_headers()
                # the engine-rate write loop: drain every event the
                # scheduler has already queued (pre-encoded window
                # frames are raw bytes) into ONE chunked write — the
                # socket sees at most one syscall per window, and a
                # briefly-stalled reader catches up in one write
                # instead of one per missed event
                ev = first
                terminal = False
                while not terminal:
                    parts = []
                    while True:
                        if isinstance(ev, bytes):
                            parts.append(ev)
                        else:
                            parts.append(
                                (json.dumps(ev) + "\n").encode())
                            if "done" in ev or "error" in ev:
                                terminal = True
                                break
                        try:
                            ev = req.events.get_nowait()
                        except queue.Empty:
                            break
                    payload = b"".join(parts)
                    t_w = time.perf_counter()
                    self.wfile.write(b"%x\r\n" % len(payload)
                                     + payload + b"\r\n")
                    write_dt = time.perf_counter() - t_w
                    server._m_stream_write.observe(write_dt)
                    server._mark(req, "tpu_serve_stream_write",
                                 write_dt, bytes=len(payload))
                    if not terminal:
                        ev = req.events.get()
                self.wfile.write(b"0\r\n\r\n")

            def _collect(self, req: _Request, first=None):
                while True:
                    ev = first if first is not None \
                        else req.events.get()
                    first = None
                    if isinstance(ev, bytes):
                        continue  # window frames: stream-only payload
                    if "error" in ev:
                        self._send(ev.get("code", 400),
                                   "application/json",
                                   json.dumps(ev) + "\n")
                        return
                    if "done" in ev:
                        self._send(200, "application/json",
                                   json.dumps(ev) + "\n")
                        return

            def _chunk(self, text: str):
                data = text.encode()
                self.wfile.write(f"{len(data):x}\r\n".encode()
                                 + data + b"\r\n")

            def _trace_headers(self):
                """Echo the request's trace back to the caller: the
                raw id for greps (X-Trace-Id) and the propagable form
                (traceparent) for clients that keep the chain going."""
                ctx = getattr(self, "_trace", None)
                if ctx is not None:
                    self.send_header("X-Trace-Id", ctx.trace_id)
                    self.send_header("traceparent",
                                     ctx.to_traceparent())

            def _send_bytes(self, code, ctype, data: bytes):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self._trace_headers()
                self.end_headers()
                self.wfile.write(data)

            def _send(self, code, ctype, body: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self._trace_headers()
                if code == 429:
                    # OpenAI rate-limit semantics: tell the client
                    # when to come back instead of letting it hammer
                    self.send_header("Retry-After", "1")
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):
                log.debug("serve-http: " + fmt, *args)

        self._httpd = _PooledHTTPServer((host, port), Handler,
                                        workers=self.max_connections,
                                        shed_counter=self._shed_conns,
                                        recorder=self.recorder)
        threading.Thread(target=self._httpd.serve_forever,
                         name="serve-http", daemon=True).start()
        self._scheduler = threading.Thread(
            target=self._scheduler_supervisor, name="engine-scheduler",
            daemon=True)
        self._scheduler.start()
        self.tsdb.start(self.alert_interval_s)
        self.profiler.start()
        if self._incidents is not None:
            self._incidents.start()
        log.info("serving engine on http://%s:%d", host, self.port)
        return self

    @property
    def port(self) -> int:
        """Actual bound port (differs from the requested one for 0)."""
        return self._httpd.server_address[1] if self._httpd else 0

    def healthy(self) -> bool:
        """Liveness: the scheduler is (or can still be) driving the
        engine.  False once the supervisor declared it dead or the
        thread vanished without the stop flag."""
        if self._sched_dead:
            return False
        t = self._scheduler
        if t is None:
            return True  # not started yet / stopped cleanly
        return t.is_alive() or self._stop.is_set()

    def stop(self) -> None:
        self.tsdb.stop()
        self.profiler.stop()
        if self._incidents is not None:
            self._incidents.stop()
        self._stop.set()
        self._work.set()  # wake an idle scheduler so it can exit
        sched = self._scheduler
        if sched is not None:
            sched.join(timeout=5)
            if sched.is_alive():
                # stuck in a long device step (e.g. a first-window
                # run_scan compile): the scheduler drains _running and
                # _head itself on exit — mutating them here would race
                # with the still-running thread (KeyError in _emit,
                # re-admitted requests)
                log.warning(
                    "scheduler busy after 5s join; clients will be "
                    "drained when the in-flight device step returns")
            else:
                self._scheduler = None
                self._drain_on_stop()  # no-op if scheduler drained
        else:
            # never started: unblock any connected client directly —
            # handler threads sit in req.events.get(), and
            # ThreadingHTTPServer.shutdown() only stops the ACCEPT loop
            self._drain_on_stop()
        bye = {"error": "server shutting down", "code": 503}
        with self._lock:
            drained, self._pending = self._pending, []
        for *_k, req in drained:
            self._push(req, dict(bye))
            self._finish_request(req, "shutdown")
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def _enqueue(self, req: _Request) -> None:
        """Admit *req* to the bounded priority heap, or answer 429.
        Overflow surfaces through the same first-event path every
        handler already checks, so all four response surfaces (native
        stream/unary, OpenAI SSE/unary) get a real 429 + Retry-After
        instead of unbounded heap growth (vLLM's admission-control
        semantics)."""
        if self._sched_dead:
            # nothing will ever pop the heap again: fail fast instead
            # of letting the client block on an event queue forever
            self._push(req, {
                "error": "engine scheduler crashed; server needs a "
                         "restart", "code": 503})
            self._finish_request(req, "shutdown")
            return
        if self._qos and not req.migrated:
            # per-tenant token-rate quota: charge the ESTIMATE (prompt
            # + requested budget, all n copies) at admission — over
            # quota is a 429 the tenant earned, not a global verdict.
            # Migrated-in requests are exempt: the prefill replica
            # already charged this request once, and the router's
            # fleet-level bucket is the global arbiter
            cost = float(
                (len(req.tokens) + req.max_new_tokens) * req.n)
            with self._lock:
                quota = self._resolve_quota(req.tenant)
                ok = quota is None or quota.try_charge(cost)
            if not ok:
                self._shed_quota.inc()
                self.recorder.record(
                    "tpu_serve_shed", trace=req.trace, rid=req.rid,
                    reason="quota", tenant=req.tenant)
                self._push(req, {
                    "error": f"tenant {req.tenant or '(default)'} "
                             "over token-rate quota; retry later",
                    "code": 429})
                self._finish_request(req, "throttled")
                return
        with self._lock:
            if len(self._pending) >= self.max_queue:
                full = True
            else:
                self._pending_seq += 1
                req._seq = self._pending_seq
                if self._qos:
                    # weighted fair queueing WITHIN a priority level:
                    # virtual finish time = max(virtual clock, the
                    # tenant's last vft) + cost/weight, so a bursting
                    # tenant queues behind its own backlog while the
                    # quiet tenant's occasional request keeps jumping
                    # near the virtual clock
                    quota = self._resolve_quota(req.tenant)
                    weight = quota.weight if quota is not None else 1.0
                    base = max(self._vtime, quota._last_vft
                               if quota is not None else 0.0)
                    req._vft = base + float(
                        (len(req.tokens) + req.max_new_tokens)
                        * req.n) / weight
                    if quota is not None:
                        quota._last_vft = req._vft
                heapq.heappush(
                    self._pending,
                    (-req.priority, req._vft, req._seq, req))
                full = False
        if full:
            self._shed_queue.inc()
            self.recorder.record("tpu_serve_shed", trace=req.trace,
                                 rid=req.rid, reason="queue")
            self._push(req, {
                "error": f"admission queue full ({self.max_queue} "
                         "requests pending); retry later",
                "code": 429})
            self._finish_request(req, "throttled")
            return
        self._work.set()

    def _attach_migration(self, req: _Request, state: dict,
                          budget) -> None:
        """Bind a migrated-in checkpoint to *req* (the /migrate
        resume half): the existing preempted-resume machinery does
        the actual engine work — ``_pull_ticket`` resumes preempted
        checkpoints before admitting anything new."""
        if req.n != 1:
            raise ValueError("migrated requests must have n=1")
        if not getattr(self.engine, "kv_paging", False):
            raise ValueError(
                "this replica cannot resume migrated KV state "
                "(kv_paging is off)")
        req.migrated = True
        req.prefill_only = False
        if budget is not None:
            # adopt the prefill replica's capped budget (prompt +
            # budget fits max_len there; configs match by contract)
            req.max_new_tokens = int(budget)
        req.budget_capped = True
        req.admitted = 1
        req.emitted[0] = 0
        req.preempted[0] = state
        self._mig_in.inc()
        self.recorder.record(
            "tpu_serve_migrate_in", trace=req.trace, rid=req.rid,
            tokens=len(req.tokens),
            outputs=len(state.get("outputs") or ()))

    # -- request plumbing ---------------------------------------------------

    def _token_byte_table(self) -> List[bytes]:
        """Per-token byte strings for grammar compilation: the
        explicit constructor table, or derived once from the tokenizer
        (the outlines/xgrammar token-to-bytes mapping)."""
        if self._token_bytes is None:
            if self.tokenizer is None:
                raise ValueError(
                    "guided decoding needs a token-to-bytes table: "
                    "start the server with --tokenizer (or "
                    "EngineServer(token_bytes=...))")
            self._token_bytes = token_bytes_of(
                self.tokenizer, self.engine.model.vocab)
        return self._token_bytes

    def _compile_grammar(self, pattern: str):
        """Pattern -> TokenDfa, cached: compilation runs on the
        HANDLER thread (it is pure — the engine is untouched), so slow
        first-compiles of big grammars never stall the scheduler loop;
        concurrent first requests may compile twice, last write wins
        harmlessly.  The engine-side register happens later, on the
        scheduler thread (see _admit_pending)."""
        with self._glock:
            tdfa = self._grammar_tdfas.get(pattern)
            if tdfa is None and self._grammar_count() >= \
                    self.max_grammars:
                self.recorder.record("tpu_serve_grammar_rejected",
                                     reason="cache_full",
                                     patterns=self.max_grammars)
                raise ValueError(
                    f"grammar cache full ({self.max_grammars} distinct "
                    "patterns); raise --max-grammars or reuse patterns")
        if tdfa is None:
            cdfa = regex_to_dfa(pattern)
            if self.max_grammar_states and \
                    len(cdfa.table) > self.max_grammar_states:
                # reject BEFORE the [N, V] token table: N states x a
                # real vocabulary is the gigabytes-of-host-memory
                # blowup the untrusted HTTP surface must not reach
                # (ADVICE r5)
                self.recorder.record("tpu_serve_grammar_rejected",
                                     reason="states_cap",
                                     states=len(cdfa.table),
                                     bound=self.max_grammar_states)
                raise ValueError(
                    f"pattern compiles to {len(cdfa.table)} DFA "
                    f"states, over the --max-grammar-states bound "
                    f"{self.max_grammar_states}; simplify the "
                    "constraint")
            tdfa = token_dfa(cdfa, self._token_byte_table(),
                             eos_id=self.engine.eos_id)
            with self._glock:
                # re-check under the lock: concurrent first requests
                # with DISTINCT new patterns each passed the earlier
                # size check and must not overshoot the bound (cache
                # entries pin engine grammar-table rows for life)
                if pattern not in self._grammar_tdfas and \
                        pattern not in self._grammar_gids and \
                        self._grammar_count() >= self.max_grammars:
                    self.recorder.record("tpu_serve_grammar_rejected",
                                         reason="cache_full",
                                         patterns=self.max_grammars)
                    raise ValueError(
                        f"grammar cache full ({self.max_grammars} "
                        "distinct patterns); raise --max-grammars or "
                        "reuse patterns")
                tdfa = self._grammar_tdfas.setdefault(pattern, tdfa)
        return tdfa

    def _grammar_count(self) -> int:
        """Distinct patterns this server has seen: registered (rows
        live in the engine's combined table) plus compiled-but-pending
        (a union — a pattern briefly sits in both mid-registration)."""
        return len(set(self._grammar_gids) | set(self._grammar_tdfas))

    def _grammar_request(self, body: dict) -> Optional[str]:
        """Extract the guided-decoding constraint from a native body:
        ``guided_regex`` (a pattern in the served regex subset),
        ``guided_json`` (true = any JSON, or a schema-subset object),
        or ``guided_choice`` (a list of literal strings — vLLM's
        choice mode, lowered as a literal alternation).  Returns the
        lowered regex pattern, or None."""
        regex = body.get("guided_regex")
        gjson = body.get("guided_json")
        choice = body.get("guided_choice")
        if sum(x is not None for x in (regex, gjson, choice)) > 1:
            raise ValueError(
                "pass exactly one of 'guided_regex', 'guided_json', "
                "'guided_choice'")
        if regex is not None:
            if not isinstance(regex, str) or not regex:
                raise ValueError(
                    "'guided_regex' must be a non-empty pattern string")
            if len(regex) > _MAX_REGEX_LEN:
                # client-supplied pattern text is attacker-controlled
                # and subset construction is super-linear in it; the
                # compiled-state bound still applies after this
                self.recorder.record("tpu_serve_grammar_rejected",
                                     reason="regex_len",
                                     chars=len(regex))
                raise ValueError(
                    f"'guided_regex' is {len(regex)} chars; the "
                    f"served bound is {_MAX_REGEX_LEN}")
            return regex
        if choice is not None:
            if (not isinstance(choice, list) or not choice or not all(
                    isinstance(c, str) and c for c in choice)):
                raise ValueError(
                    "'guided_choice' must be a non-empty list of "
                    "non-empty strings")
            from .grammar import _regex_escape

            return "(" + "|".join(
                _regex_escape(c) for c in choice) + ")"
        if gjson is None:
            return None
        if gjson is True:
            return json_value_regex()
        if isinstance(gjson, dict):
            return schema_to_regex(gjson)
        raise ValueError(
            "'guided_json' must be true or a JSON-schema object")

    def _openai_to_native(self, body: dict):
        """Translate an OpenAI /v1/completions body onto the native
        request shape.  Returns (native_body, model_name)."""
        if self.tokenizer is None:
            raise ValueError(
                "/v1/completions needs a tokenizer (start the server "
                "with --tokenizer); the native /generate endpoint "
                "speaks raw token ids")
        prompt = body.get("prompt")
        native: dict = {"detokenize": True}
        if isinstance(prompt, list) and prompt and all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in prompt):
            native["tokens"] = prompt  # OpenAI's token-array form
        elif isinstance(prompt, str):
            native["prompt"] = prompt
        else:
            raise ValueError(
                "'prompt' must be a string or a token-id array")
        def opt(key, default=None):
            # an explicit JSON null means "use the default" in the
            # OpenAI API (clients serialize unset optionals as null)
            v = body.get(key)
            return default if v is None else v

        native["max_new_tokens"] = int(
            opt("max_tokens", opt("max_completion_tokens", 16)))
        if opt("user") is not None:
            # OpenAI's end-user identity doubles as the QoS tenant
            native["tenant"] = str(opt("user"))
        if opt("session") is not None:
            # session KV tiering: the extension key `session` names the
            # conversation; scoped under `user` when both are present
            # so two users' identically-named sessions never collide
            sid = str(opt("session"))
            native["session_id"] = (f"{opt('user')}/{sid}"
                                    if opt("user") is not None else sid)
        if opt("slo_class") is not None or \
                opt("service_tier") is not None:
            # SLO class: the vLLM-style extension key, or OpenAI's
            # service_tier as the nearest native concept
            native["slo_class"] = str(
                opt("slo_class", opt("service_tier")))
        # OpenAI defaults temperature to 1.0 (sampled); clients wanting
        # greedy pass 0 explicitly, exactly as with OpenAI/vLLM
        native["temperature"] = float(opt("temperature", 1.0))
        if opt("top_p") is not None:
            native["top_p"] = float(opt("top_p"))
        if opt("n") is not None:
            native["n"] = int(opt("n"))
        if opt("seed") is not None:
            native["seed"] = int(opt("seed"))
        if opt("presence_penalty") is not None:
            native["presence_penalty"] = float(opt("presence_penalty"))
        if opt("frequency_penalty") is not None:
            native["frequency_penalty"] = float(
                opt("frequency_penalty"))
        if opt("logprobs") is not None:
            # OpenAI logprobs=0 means "chosen token's logprob, no
            # alternatives" — the engine's 0 means OFF, so request
            # top-1 and trim the alternatives in the response
            # (_lp_count carries the client-requested count through to
            # the response builder; _parse_request ignores it)
            native["_lp_count"] = int(opt("logprobs"))
            native["logprobs"] = max(1, native["_lp_count"])
        stop = opt("stop")
        if stop is not None:
            native["stop"] = [stop] if isinstance(stop, str) else stop
        if opt("logit_bias") is not None:
            native["logit_bias"] = opt("logit_bias")
        if opt("min_tokens") is not None:  # vLLM's OpenAI extension
            native["min_tokens"] = int(opt("min_tokens"))
        rf = opt("response_format")
        if rf is not None:
            # OpenAI guided decoding: json_object constrains to any
            # JSON value, json_schema to the declared schema subset
            if not isinstance(rf, dict) or "type" not in rf:
                raise ValueError(
                    "'response_format' must be an object with 'type'")
            kind = rf["type"]
            if kind == "json_object":
                # the OpenAI contract is an OBJECT, not any JSON value
                native["guided_json"] = {"type": "object"}
            elif kind == "json_schema":
                js = rf.get("json_schema")
                schema = js.get("schema") if isinstance(js, dict) \
                    else None
                if not isinstance(schema, dict):
                    # a 400 beats silently under-constraining: the
                    # client believes its schema is enforced
                    raise ValueError(
                        "'response_format.json_schema.schema' must be "
                        "a schema object")
                native["guided_json"] = schema
            elif kind != "text":
                raise ValueError(
                    f"unsupported response_format type {kind!r} "
                    "(text, json_object, json_schema)")
        if opt("guided_regex") is not None:  # vLLM's OpenAI extension
            native["guided_regex"] = opt("guided_regex")
        if opt("guided_choice") is not None:  # vLLM's OpenAI extension
            native["guided_choice"] = opt("guided_choice")
        if opt("echo"):
            native["_echo"] = True
            if native.get("logprobs"):
                # OpenAI echo+logprobs covers the PROMPT tokens too
                # (first entry null): ride the engine's prompt_logprobs
                # (prefill-logit scoring) so the response aligns
                # tokens/token_logprobs with the echoed text
                native["prompt_logprobs"] = native["logprobs"]
        so = opt("stream_options")
        if so is not None:
            if not bool(body.get("stream", False)):
                raise ValueError(
                    "'stream_options' is only allowed with "
                    "'stream': true")
            if not isinstance(so, dict):
                raise ValueError("'stream_options' must be an object")
            if so.get("include_usage"):
                native["_include_usage"] = True
        return native, str(opt("model", "default"))

    def _openai_chat_to_native(self, body: dict):
        """Translate an OpenAI /v1/chat/completions body: the
        tokenizer's chat template renders the messages into the
        prompt, everything else rides the completions translation."""
        if self.tokenizer is None:
            raise ValueError(
                "/v1/chat/completions needs a tokenizer (start the "
                "server with --tokenizer)")
        template = getattr(self.tokenizer, "apply_chat_template", None)
        if template is None:
            raise ValueError(
                "the loaded tokenizer has no chat template; use "
                "/v1/completions")
        messages = body.get("messages")
        if (not isinstance(messages, list) or not messages or not all(
                isinstance(m, dict)
                and isinstance(m.get("role"), str)
                and isinstance(m.get("content"), str)
                for m in messages)):
            raise ValueError(
                "'messages' must be a non-empty list of "
                "{role, content} objects")
        if body.get("echo"):
            raise ValueError(
                "'echo' is a completions-only parameter")
        prompt = template(messages, tokenize=False,
                          add_generation_prompt=True)
        flat = dict(body)
        flat.pop("messages")
        # chat templates already emit BOS/special markers: re-encoding
        # with default special-token addition would double the BOS, so
        # pre-encode here (token-array prompts skip encode entirely)
        try:
            ids = self.tokenizer.encode(prompt,
                                        add_special_tokens=False)
        except TypeError:  # tokenizer without the kwarg (test fakes)
            ids = self.tokenizer.encode(prompt)
        flat["prompt"] = [int(t) for t in ids]
        # chat logprobs semantics: a BOOLEAN plus top_logprobs (int),
        # not the completions integer — translate before delegating
        lpb = flat.pop("logprobs", None)
        top_n = flat.pop("top_logprobs", None)
        if lpb:
            flat["logprobs"] = int(top_n or 0)
        return self._openai_to_native(flat)

    def _parse_request(self, body: dict, trace=None) -> _Request:
        tokens = body.get("tokens")
        prompt = body.get("prompt")
        detokenize = bool(body.get("detokenize", prompt is not None))
        if prompt is not None:
            if tokens is not None:
                raise ValueError("pass 'prompt' OR 'tokens', not both")
            if not isinstance(prompt, str) or not prompt:
                raise ValueError("'prompt' must be a non-empty string")
            if self.tokenizer is None:
                raise ValueError(
                    "'prompt' strings need a tokenizer (start the "
                    "server with --tokenizer); pass 'tokens' instead")
            tokens = [int(t) for t in self.tokenizer.encode(prompt)]
        if detokenize and self.tokenizer is None:
            raise ValueError("'detokenize' needs a tokenizer")
        if (not isinstance(tokens, list) or not tokens
                or not all(isinstance(t, int)
                           and not isinstance(t, bool) for t in tokens)):
            # bool is an int subclass: JSON `true` would silently
            # become token id 1 instead of a 400 (same guard as 'stop')
            raise ValueError("'tokens' must be a non-empty int list")
        max_new = int(body.get("max_new_tokens", self.default_max_new))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        min_new = int(body.get("min_tokens", 0))
        if min_new < 0:
            raise ValueError("min_tokens must be >= 0")
        if min_new > max_new:
            raise ValueError(
                f"min_tokens {min_new} exceeds max_new_tokens "
                f"{max_new}")
        top_k = body.get("top_k")
        adapter = body.get("adapter")
        logprobs = body.get("logprobs")
        prompt_logprobs = body.get("prompt_logprobs")
        # copies admit incrementally, so n may exceed the slot count;
        # the cap is only a sanity bound against runaway requests
        n = int(body.get("n", 1))
        if not 1 <= n <= 128:
            raise ValueError(f"n={n} outside [1, 128]")
        logit_bias = body.get("logit_bias")
        if logit_bias == {}:
            logit_bias = None  # OpenAI treats an empty object as unset
        if logit_bias is not None:
            if not isinstance(logit_bias, dict):
                raise ValueError(
                    "'logit_bias' must be a {token id: bias} object")
            try:
                # JSON object keys are strings (OpenAI sends them so)
                logit_bias = {int(k): float(v)
                              for k, v in logit_bias.items()}
            except (TypeError, ValueError):
                raise ValueError(
                    "'logit_bias' keys must be token ids and values "
                    "numbers")
        stop = body.get("stop")
        stop_strs: Optional[List[str]] = None
        if stop is not None:
            if not isinstance(stop, list) or not all(
                    (isinstance(t, int) and not isinstance(t, bool))
                    or isinstance(t, str)
                    for t in stop):
                # bool is an int subclass: JSON `true` would silently
                # become token id 1 instead of a 400
                raise ValueError(
                    "'stop' must be a list of token ids and/or strings")
            stop_strs = [s for s in stop if isinstance(s, str) and s]
            stop = [t for t in stop if isinstance(t, int)]
            if stop_strs and self.tokenizer is None:
                raise ValueError(
                    "stop STRINGS need a tokenizer (start the server "
                    "with --tokenizer); pass stop token ids instead")
            stop = stop or None
            stop_strs = stop_strs or None
        grammar_key = grammar_tdfa = None
        pattern = self._grammar_request(body)
        if pattern is not None:
            if self.engine.eos_id is None:
                raise ValueError(
                    "guided decoding needs an engine eos id (the "
                    "grammar gates completion on it)")
            grammar_key = pattern
            with self._glock:
                registered = pattern in self._grammar_gids
            if not registered:
                # compiles (or cache-hits) here on the handler thread;
                # regex syntax errors and vocabulary dead-ends surface
                # as this request's 400, never a scheduler stall.
                # Registered patterns skip the compile entirely — the
                # engine's combined table already holds their rows
                grammar_tdfa = self._compile_grammar(pattern)
        req = _Request(
            tokens=tokens,
            max_new_tokens=max_new,
            temperature=float(body.get("temperature", 0.0)),
            top_k=None if top_k is None else int(top_k),
            top_p=float(body.get("top_p", 1.0)),
            min_p=float(body.get("min_p", 0.0)),
            presence_penalty=float(body.get("presence_penalty", 0.0)),
            frequency_penalty=float(body.get("frequency_penalty", 0.0)),
            repetition_penalty=float(
                body.get("repetition_penalty", 1.0)),
            adapter=None if adapter is None else int(adapter),
            stop=stop,
            stop_strs=stop_strs,
            detokenize=detokenize,
            logit_bias=logit_bias,
            min_tokens=min_new,
            ignore_eos=bool(body.get("ignore_eos", False)),
            seed=(None if body.get("seed") is None
                  else int(body["seed"])),
            priority=int(body.get("priority", 0)),
            tenant=str(body.get("tenant", "") or ""),
            # conversation key for the session KV tier: purely
            # opt-in, absent/empty means the request is anonymous
            session=str(
                body.get("session_id", body.get("session", "")) or ""),
            # free-form on the wire, BOUNDED at record time: an
            # unknown class lands under the "other" label, never a
            # new series (the O1/slo contract)
            slo_class=str(body.get("slo_class", "") or ""),
            logprobs=None if logprobs is None else int(logprobs),
            prompt_logprobs=(None if prompt_logprobs is None
                             else int(prompt_logprobs)),
            n=n,
            grammar_key=grammar_key,
            grammar_tdfa=grammar_tdfa,
            stream=bool(body.get("stream", True)),
            per_token=bool(body.get("per_token", False)),
            # bounded: the slow-client disconnect policy (see _push)
            events=queue.Queue(self.max_events),
        )
        # request tracing: the span starts at parse (its duration is
        # the full wire-visible latency) and ends exactly once at the
        # terminal outcome; the rid tags every structured log line
        # (process-wide counter: unique across servers in one process).
        # The trace context (continued from the caller's traceparent or
        # a fresh root) rides the span into its log line, the request
        # histogram's exemplar, and the flight-recorder event
        req.rid = f"req-{next(_RID_COUNTER):x}"
        req.trace = trace if trace is not None else obs.new_trace()
        req.t_arrival = time.perf_counter()
        req.span = obs.Span(
            "tpu_serve_request",
            histogram=getattr(self, "_m_request", None),
            request_id=req.rid, logger=log, trace=req.trace,
            recorder=getattr(self, "recorder", None),
        ).annotate(prompt_tokens=len(tokens), n=n)
        if body.get("prefill_only"):
            # internal router marker (disagg path): run prefill, then
            # export the checkpoint instead of decoding.  Eligibility
            # is decided HERE — an ineligible request silently serves
            # normally and the router passes the stream through
            # (graceful degradation beats a hard 4xx mid-topology)
            if (getattr(self.engine, "kv_paging", False) and n == 1
                    and self.replica_role != "decode"):
                req.prefill_only = True
            else:
                self.recorder.record(
                    "tpu_serve_migrate_declined", trace=req.trace,
                    rid=req.rid,
                    reason=("role" if self.replica_role == "decode"
                            else "paging" if not getattr(
                                self.engine, "kv_paging", False)
                            else "multi_copy"))
        return req

    def stats(self) -> dict:
        st = dict(self.engine.stats())
        with self._glock:
            grammar_patterns = self._grammar_count()
        st.update({
            "pending_requests": len(self._pending),
            # distinct REQUESTS (an n>1 request occupies n slots)
            "running_requests": len(
                {id(r) for r, _ in self._running.values()}),
            "running_copies": len(self._running),
            "admitting_copies": len(self._tickets),
            "requests_served": self._requests_served,
            "requests_rejected": self._requests_rejected,
            # promoted counters read back so /stats and /metrics agree
            "requests_throttled": self._requests_throttled,
            "requests_dropped": self._requests_dropped,
            "client_abandons": int(self._m_abandons.value),
            "grammar_patterns": grammar_patterns,
            "window": self.window,
            "max_queue": self.max_queue,
        })
        if self._httpd is not None:
            st.update(self._httpd.pool_stats())
        return st

    def profile(self, seconds: float) -> dict:
        """Capture one jax.profiler trace of *seconds* into
        ``--profile-dir`` (the /debug/profile handler).  Single-flight:
        a second capture while one is running raises RuntimeError
        (jax's profiler is process-global — two overlapping traces
        corrupt each other).  Blocking by design: the handler's worker
        sleeps through the capture and answers with the dump dir, so
        callers (and tests) need no polling protocol.  CPU-safe — the
        profiler records host traces without an accelerator."""
        if not self.profile_dir:
            raise ValueError(
                "profiling is not configured: start the server with "
                "--profile-dir")
        if not 0 < seconds <= 60:
            raise ValueError("seconds must be in (0, 60]")
        if not self._profile_lock.acquire(blocking=False):
            raise RuntimeError("a profile capture is already running")
        try:
            import jax

            # compose with the continuous sampler (PR 19): the ring
            # sampler parks for the capture window — suspended ticks
            # are still counted, so the profile timeline shows an
            # honest gap instead of samples of the capture machinery
            t0 = time.perf_counter()
            with self.profiler.suspend(reason="jax_profiler"):
                jax.profiler.start_trace(self.profile_dir)
                try:
                    time.sleep(seconds)
                finally:
                    jax.profiler.stop_trace()
            dt = time.perf_counter() - t0
        finally:
            self._profile_lock.release()
        self._m_profile.inc()
        self.recorder.record("tpu_serve_profile", seconds=seconds,
                             duration_s=dt, dir=self.profile_dir)
        return {"ok": True, "seconds": seconds,
                "profile_dir": self.profile_dir}

    def statz(self) -> dict:
        """The router tier's load signal: one SMALL fixed-schema JSON
        snapshot (queue depth, in-flight copies, KV pool occupancy,
        shed counts, scheduler health) assembled from the same host
        ints /metrics bridges — so the router never parses Prometheus
        text on the routing hot path, and the lock-step test can pin
        this surface against the tpu_serving_* families."""
        st = self.stats()
        return {
            "scheduler_alive": self.healthy(),
            "queue_depth": st["pending_requests"],
            "in_flight": (st["running_copies"]
                          + st["admitting_copies"]),
            "capacity": st["n_slots"],
            "kv_pages": st.get("kv_pages", 0),
            "kv_pages_free": st.get("kv_pages_free", 0),
            "requests_served": st["requests_served"],
            # disaggregated serving (router v2): the role this replica
            # registered as, and the migration ledger in lock-step
            # with tpu_serve_migrations_total{direction}
            "role": self.replica_role,
            "migrations": {
                "out": int(self._mig_out.value),
                "in": int(self._mig_in.value),
            },
            "shed": {
                "connections": int(self._shed_conns.value),
                "queue": int(self._shed_queue.value),
                "quota": int(self._shed_quota.value),
            },
            # session KV tier occupancy (fixed schema even when the
            # tier is off, so /fleet/statz aggregation never branches)
            "kv_tiers": (self._session_store.stats()
                         if self._session_store is not None
                         else empty_tier_stats()),
            # the fixed-schema goodput block the router's /fleet/statz
            # aggregates and the autoscaler will key scaling on
            "goodput": self._slo.summary(),
            # firing/pending alert roll-up (PR 18): rides the same
            # heartbeat the goodput block does, so the router's
            # /fleet/statz can aggregate firing_alerts without an
            # extra fan-out poll
            "alerts": self.alerts.brief(),
        }

    def slo_miss_traces(self, top: int = 5) -> dict:
        """The incident bundle's span-attribution payload: the slowest
        *top* requests that missed their SLO (per the journal's
        ``tpu_serve_slo_miss`` markers), each with every ring event of
        its trace — ``obs_query --incident`` stitches these back into
        span trees offline."""
        misses = self.recorder.events(name="tpu_serve_slo_miss")

        def _dur(ev: dict) -> float:
            attrs = ev.get("attrs")
            if isinstance(attrs, dict):
                try:
                    return float(attrs.get("duration_s", 0.0))
                except (TypeError, ValueError):
                    return 0.0
            return 0.0

        misses.sort(key=_dur, reverse=True)
        out = []
        for ev in misses[:top]:
            attrs = ev.get("attrs")
            attrs = attrs if isinstance(attrs, dict) else {}
            tid = ev.get("trace_id") or ""
            events = (self.recorder.events(trace_id=str(tid))
                      if tid else [ev])
            out.append({
                "rid": attrs.get("rid", ""),
                "trace_id": tid,
                "duration_s": _dur(ev),
                "slo_class": attrs.get("slo_class", ""),
                "outcome": attrs.get("outcome", ""),
                "events": events,
            })
        return {"schema": "tpu-incident-traces/v1", "misses": out}

    # -- router registration (multi-replica serving) ------------------------

    def start_registration(self, router: str,
                           advertise: Optional[str] = None,
                           replica_id: Optional[str] = None,
                           model: str = "",
                           interval_s: float = 2.0) -> None:
        """Self-register with a router tier and keep heartbeating
        (slice-coordinator-style membership for the serving data
        plane).  *router* is ``http://host:port`` (or bare
        ``host:port``); *advertise* is the address the ROUTER should
        dial back (default ``127.0.0.1:<bound port>`` — wrong across
        hosts, so deployments set it to the pod IP).  Heartbeats carry
        an inline statz snapshot so the router's load signal freshens
        without waiting for its next poll.  A down router never hurts
        serving: failures are counted + logged and the loop just tries
        again next interval (retried within a beat by the shared
        RetryPolicy).  Call after :meth:`start`."""
        target = router
        if target.startswith("http://"):
            target = target[len("http://"):]
        target = target.rstrip("/")
        host, _, port_s = target.rpartition(":")
        if not host or not port_s.isdigit():
            raise ValueError(
                f"--register-with {router!r} must be http://host:port")
        addr = advertise or f"127.0.0.1:{self.port}"
        rid = replica_id or addr
        self._replica_id = rid
        from tpu_k8s_device_plugin import resilience

        policy = resilience.RetryPolicy(
            max_attempts=2, initial_backoff_s=0.1, max_backoff_s=0.5)
        rmetrics = resilience.ResilienceMetrics(self.registry)

        def beat_once() -> float:
            """One registration POST; returns the router's interval
            hint (seconds)."""
            import http.client

            conn = http.client.HTTPConnection(host, int(port_s),
                                              timeout=5.0)
            try:
                conn.request(
                    "POST", "/register",
                    json.dumps({
                        "replica_id": rid,
                        "address": addr,
                        "model": model,
                        "capacity": self.engine.n_slots,
                        "role": self.replica_role,
                        "statz": self.statz(),
                    }),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise OSError(
                        f"router answered {resp.status}: "
                        f"{body[:120]!r}")
                out = json.loads(body)
                return float(out.get("interval_s", interval_s))
            finally:
                conn.close()

        def loop() -> None:
            wait = interval_s
            while not self._stop.wait(wait):
                try:
                    hint = policy.call(
                        beat_once, op="serve.register",
                        retry_on=(OSError, ValueError),
                        stop=self._stop, metrics=rmetrics,
                        recorder=self.recorder)
                    wait = max(0.2, min(interval_s, hint))
                except resilience.CircuitOpenError:
                    return  # stop() aborted the retry sleep
                except (OSError, ValueError) as e:
                    # the router being down is ITS outage, not ours:
                    # serving keeps serving, the loop keeps knocking
                    resilience.suppressed(
                        "serve.register", e, logger=log,
                        metrics=rmetrics)
            log.debug("registration loop stopped")

        try:
            policy.call(beat_once, op="serve.register",
                        retry_on=(OSError, ValueError),
                        stop=self._stop, metrics=rmetrics,
                        recorder=self.recorder)
            log.info("registered with router %s as %s (%s)",
                     router, rid, addr)
        except (OSError, ValueError, resilience.CircuitOpenError) as e:
            log.warning("initial router registration failed (%s); "
                        "will keep retrying every %.1fs", e,
                        interval_s)
        self._register_thread = threading.Thread(
            target=loop, name="serve-register", daemon=True)
        self._register_thread.start()

    def render_metrics(self, openmetrics: bool = False) -> str:
        """The serving /metrics body: the obs registry (request spans,
        TTFT / per-token / queue-wait / admit / stream-write
        histograms, shed + drop counters) plus every numeric stats()
        entry bridged as ``tpu_serving_<key>``.  *openmetrics* adds
        trace-id exemplars + the ``# EOF`` terminator (serve it only
        under the OpenMetrics content type).

        Rename (PR 3, promlint): bridged MONOTONIC stats now carry the
        ``_total`` suffix counters require —
        ``tpu_serving_requests_served`` is
        ``tpu_serving_requests_served_total`` and so on; gauges keep
        their old names.

        The stats bridge itself runs as a registry collect hook (PR
        18) so the TSDB's background sampling tick retains fresh
        ``tpu_serving_*`` values too, not just HTTP scrapes; the
        render is accounted via :class:`obs.ScrapeMeta`
        (``tpu_scrape_*``)."""
        return self.scrape_meta.render(openmetrics=openmetrics)

    def _bridge_stats(self) -> None:
        """Registry collect hook: mirror every numeric stats() entry
        as a ``tpu_serving_*`` family (gauge or ``_total`` counter)."""
        st = self.stats()
        reg = self.registry
        for k, v in st.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if k in _GAUGE_STATS:
                reg.gauge(f"tpu_serving_{k}",
                          f"Server/engine gauge '{k}' (see /stats)."
                          ).set(v)
            else:
                name = f"tpu_serving_{k}"
                if not name.endswith("_total"):
                    name += "_total"
                reg.counter(
                    name,
                    f"Server/engine counter '{k}' (see /stats)."
                )._set(v)


def enable_compile_cache(path: str) -> bool:
    """Point jax's persistent compilation cache at *path* (cross-
    process: every jit/pjit executable serializes there and later
    processes LOAD instead of recompiling).  This is what makes a
    fresh autoscaled replica serving in seconds instead of paying the
    per-shape warmup storm — the scan-window variants, the packed
    shape set, and the extend/prefill shapes all land in the cache on
    the first boot and every subsequent boot (same binary, same
    config) hits it.  Must run BEFORE any jit compiles (the CLI calls
    it before building the model).  The entry-size/compile-time floors
    drop to zero so small CPU executables cache too — the bench's
    cold-start phase depends on that.  Returns False (logged, never
    fatal) when the running jax predates the knobs: a missing cache
    only costs warmup time."""
    try:
        import jax as _jax

        _jax.config.update("jax_compilation_cache_dir", path)
        _jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1)
        _jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        return True
    except Exception as e:  # pragma: no cover - jax-version dependent
        log.warning("persistent compile cache unavailable (%s); "
                    "replica cold starts pay full compile time", e)
        return False


def main(argv=None) -> int:
    """CLI: build a Llama-family engine and serve it.  The k8s example
    (example/native-serve/deployment.yaml) runs exactly this."""
    from .bench_serving import CONFIGS, build_model_and_params

    p = argparse.ArgumentParser(prog="tpu-serve")
    p.add_argument("--config", choices=sorted(CONFIGS), default="tiny")
    p.add_argument("--quantized", action="store_true",
                   help="weight-only int8")
    p.add_argument("--int4", action="store_true",
                   help="weight-only int4")
    p.add_argument("--n-slots", type=int, default=8)
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel ways: shard params/KV over a "
                        "model-axis mesh of the first N visible chips "
                        "(the native analog of vLLM's "
                        "--tensor-parallel-size)")
    p.add_argument("--max-len", type=int, default=2048)
    p.add_argument("--max-new-tokens", type=int, default=256,
                   help="default per-request budget")
    p.add_argument("--window", type=int, default=DEFAULT_WINDOW)
    p.add_argument("--prefix-chunk", type=int, default=0,
                   metavar="N",
                   help="admission/prefix-cache grid: prompts prefill "
                        "in N-token chunks and APC matches floor to "
                        "whole chunks (must divide --max-len); 0 = "
                        "engine auto (32-grid when max_len allows)")
    p.add_argument("--no-interleave", action="store_true",
                   help="disable iteration-level prefill/decode "
                        "interleaving (admissions then run fully "
                        "between decode windows, the pre-scheduler "
                        "cadence; outputs are identical either way)")
    p.add_argument("--prefill-chunks", type=int,
                   default=DEFAULT_PREFILL_BUDGET, metavar="K",
                   help="prefill chunks dispatched into one open "
                        "decode window (interleave granularity): "
                        "higher admits long prompts faster, lower "
                        "bounds how long a window's harvest can be "
                        "delayed behind prefill")
    p.add_argument("--packed-prefill", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="ragged packed prefill (default on): "
                        "concurrent admissions' prefill chunks batch "
                        "into ONE extend dispatch per chunk-round "
                        "(pack sizes 2..--max-pack, a fixed compiled "
                        "shape set); outputs byte-identical either "
                        "way")
    p.add_argument("--overlap-dispatch", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="double-buffered dispatch/harvest (default "
                        "on): dispatch decode window N+1 before "
                        "streaming window N so host stream writes "
                        "overlap device compute; auto-falls back to "
                        "the serial cadence while any sampled request "
                        "is live (outputs byte-identical either way)")
    p.add_argument("--fused-decode", default=False,
                   action=argparse.BooleanOptionalAction,
                   help="fused decode loop (default off): decode "
                        "windows carry per-slot eos/stop/budget finish "
                        "flags on-device, harvest slices kept prefixes "
                        "columnar-side instead of re-scanning tokens "
                        "on host, and dispatch-ahead overlap extends "
                        "to SAMPLED windows (outputs byte-identical "
                        "either way — the fused equivalence suite "
                        "pins it)")
    p.add_argument("--max-pack", type=int, default=DEFAULT_MAX_PACK,
                   metavar="K",
                   help="packed-prefill width cap: each pack size in "
                        "2..K is one compiled extend shape "
                        "(warm_scheduler pre-compiles the set)")
    p.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                   help="persistent cross-process XLA compile cache "
                        "(env: TPU_DP_COMPILE_CACHE_DIR): first boot "
                        "fills it, every later boot of the same "
                        "config loads executables instead of "
                        "recompiling — a fresh autoscaled replica is "
                        "serving in seconds instead of paying the "
                        "per-shape warmup storm.  Mount it on shared "
                        "or node-local storage that survives pod "
                        "churn")
    p.add_argument("--schedule-watchdog", type=float, default=0.0,
                   metavar="SECONDS",
                   help="fail a scheduler iteration stuck past this "
                        "deadline (503 + supervised restart instead "
                        "of a silent hang); 0 disables — first-window "
                        "compiles can legitimately take tens of "
                        "seconds, so size it to your steady state")
    p.add_argument("--logprobs-k", type=int, default=5,
                   help="engine-wide top-k logprobs cap (requests ask "
                        "for n <= k; 0 disables the stats entirely)")
    p.add_argument("--draft-config", choices=sorted(CONFIGS), default=None,
                   help="speculative draft model (e.g. llama3-1b for "
                        "llama3-8b); greedy requests decode in "
                        "propose/verify rounds")
    p.add_argument("--gamma", type=int, default=4,
                   help="draft proposals per speculative round")
    p.add_argument("--spec-ngram", type=int, default=0, metavar="N",
                   help="draft-free prompt-lookup speculation with "
                        "N-gram matching (vLLM's [ngram] mode); "
                        "mutually exclusive with --draft-config")
    p.add_argument("--max-grammars", type=int, default=64,
                   help="distinct guided-decoding patterns cached per "
                        "server lifetime (each occupies engine grammar "
                        "table rows)")
    p.add_argument("--max-grammar-states", type=int, default=8192,
                   help="reject guided-decoding patterns whose "
                        "char-DFA exceeds this many states (before "
                        "the [N, V] token table is built); 0 disables")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="admission queue bound: requests past it get "
                        "429 + Retry-After instead of unbounded heap "
                        "growth")
    p.add_argument("--max-connections", type=int, default=64,
                   help="HTTP worker pool size (fixed thread count); "
                        "connections past 2x this are shed with 429 "
                        "at accept")
    p.add_argument("--client-timeout", type=float, default=120.0,
                   help="per-connection socket timeout in seconds: a "
                        "stuck peer frees its pool worker")
    p.add_argument("--flight-record-dir", default=None, metavar="DIR",
                   help="dump the flight-recorder event journal (JSON "
                        "lines) to DIR on exit/SIGTERM — the black-box "
                        "post-mortem; unset disables the dump (the "
                        "in-memory ring and /debug/traces stay on)")
    p.add_argument("--flight-dump-keep", type=int, default=20,
                   metavar="K",
                   help="keep only the newest K flight-record dump "
                        "files in --flight-record-dir (older ones are "
                        "deleted at dump time; deletions count in "
                        "tpu_flight_dump_gc_total)")
    p.add_argument("--slo", action="append", default=None,
                   metavar="CLASS=TTFT_MS[:DEADLINE_MS]",
                   help="declare an SLO class (repeatable), e.g. "
                        "'interactive=250' (TTFT target) or "
                        "'batch=0:60000' (completion deadline); "
                        "default: interactive=2500 + batch=0:60000. "
                        "Requests pick a class with \"slo_class\"; "
                        "unknown names land under the bounded 'other' "
                        "label")
    p.add_argument("--slo-window", type=float, default=60.0,
                   metavar="S",
                   help="rolling window (seconds) for the goodput and "
                        "error-budget burn-rate gauges")
    p.add_argument("--alert-rules", default=None, metavar="FILE",
                   help="JSON alert-rule file ({\"rules\": [...]}) "
                        "evaluated by the in-process alert engine on "
                        "top of the burn-rate rules derived from every "
                        "--slo class; firing state serves on /alerts")
    p.add_argument("--alert-interval", type=float, default=5.0,
                   metavar="S",
                   help="TSDB sampling / alert evaluation tick "
                        "(seconds)")
    p.add_argument("--alert-window-scale", type=float, default=1.0,
                   metavar="X",
                   help="scale factor on the derived burn-rate rule "
                        "windows (5m/1h/6h * X) — CI and soak tests "
                        "shrink them to fire within seconds")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="enable GET /debug/profile?seconds=N: dump "
                        "jax.profiler traces there (single-flight; "
                        "env TPU_DP_PROFILE_DIR)")
    p.add_argument("--incident-dir", default=None, metavar="DIR",
                   help="alert-triggered incident bundles: when a "
                        "page-severity alert fires, write one atomic "
                        "directory there (alert history, journal "
                        "dump, TSDB snapshot, continuous-profile "
                        "slice, statz, slowest SLO-missed traces); "
                        "rate-limited per alert, GC'd newest-K "
                        "(env TPU_DP_INCIDENT_DIR)")
    p.add_argument("--profiler-hz", type=float, default=19.0,
                   metavar="HZ",
                   help="continuous sampling profiler rate for "
                        "GET /debug/pprof (default 19 — prime, so the "
                        "sampler cannot phase-lock a periodic loop)")
    p.add_argument("--flight-record-capacity", type=int, default=4096,
                   help="flight-recorder ring size in events "
                        "(drop-oldest past it)")
    p.add_argument("--fault-spec", default=None, metavar="SPEC",
                   help="arm deterministic fault injection (chaos "
                        "testing ONLY): op:kind:arg[;...] — e.g. "
                        "'serve.step:error:0.02'.  Unset (the "
                        "default) leaves every hook a no-op attribute "
                        "check.  Env: TPU_DP_FAULTS")
    p.add_argument("--fault-seed", type=int, default=None,
                   help="RNG seed for --fault-spec probabilities "
                        "(default 0; env: TPU_DP_FAULT_SEED)")
    p.add_argument("--jump-len", type=int, default=8,
                   help="structural jump-ahead width: up to this many "
                        "DFA-forced tokens (a schema's keys and "
                        "punctuation) commit per multi-token extend")
    p.add_argument("--kv-paging", action="store_true",
                   help="paged KV cache: fixed-size pages + free-list "
                        "allocator with copy-on-write prefix sharing "
                        "and preemption-by-page-eviction (outputs "
                        "bit-identical to the contiguous default)")
    p.add_argument("--kv-page-size", type=int, default=0, metavar="N",
                   help="KV page size in tokens (0 = the admission "
                        "chunk; must divide it and --max-len)")
    p.add_argument("--kv-pages", type=int, default=0, metavar="P",
                   help="physical KV pool size in pages (0 = full "
                        "reservation, n_slots * max_len/page; smaller "
                        "oversubscribes — shared prefixes and "
                        "preemption absorb the pressure)")
    p.add_argument("--kv-dtype", choices=["int8"], default=None,
                   help="quantize paged KV pool storage (int8 values "
                        "+ per-row f32 scales; ~47%% of the bf16 "
                        "bytes, NOT bit-identical to contiguous)")
    p.add_argument("--session-tier", action="store_true",
                   help="three-tier session KV store keyed by the "
                        "optional session_id request field: parked "
                        "device pages -> bounded host-RAM pool -> "
                        "crash-safe --session-dir spill files; a "
                        "returning session resumes its KV instead of "
                        "re-prefilling (needs --kv-paging)")
    p.add_argument("--session-dir", default=None, metavar="DIR",
                   help="disk spill directory for --session-tier "
                        "(atomic tmp->rename .kvs files that survive "
                        "process death; unset disables the disk tier)")
    p.add_argument("--session-host-mb", type=int, default=256,
                   help="host-RAM tier cap in MiB — over it the "
                        "oldest sessions spill to disk or evict")
    p.add_argument("--session-disk-keep", type=int, default=512,
                   help="newest-K GC bound on spilled .kvs files")
    p.add_argument("--session-idle", type=float, default=30.0,
                   metavar="SECONDS",
                   help="idle seconds (seeded +/-10%% jitter) before "
                        "a parked device session demotes to host RAM")
    p.add_argument("--session-host-idle", type=float, default=120.0,
                   metavar="SECONDS",
                   help="idle seconds (seeded jitter) before a "
                        "host-tier session spills to --session-dir")
    p.add_argument("--session-seed", type=int, default=0,
                   help="RNG seed for the tier timers' jitter (keeps "
                        "demotion schedules reproducible in tests)")
    p.add_argument("--tenant-quota", action="append", default=None,
                   metavar="NAME=RATE[:BURST[:WEIGHT]]",
                   help="per-tenant QoS (repeatable; NAME '*' is the "
                        "default tenant): token-rate quota (tokens/s "
                        "over prompt+budget estimates, 429 past it) "
                        "and weighted fair queueing in the admission "
                        "heap; requests carry 'tenant' (native) or "
                        "'user' (OpenAI)")
    p.add_argument("--prefix-registry-max", type=int, default=256,
                   help="LRU cap on registered prefixes + the bound "
                        "feeding tpu_serve_prefix_evictions_total "
                        "(each entry pins a full-length KV copy)")
    p.add_argument("--checkpoint", default=None, metavar="DIR",
                   help="serve REAL weights: an orbax checkpoint dir "
                        "(workloads.checkpoint layout, state "
                        "{'params': ...} in the bf16 train layout); "
                        "--quantized/--int4 quantize after restore. "
                        "Without it the CLI serves random weights in "
                        "the benchmark posture. (--draft-config drafts "
                        "stay random either way — correctness never "
                        "depends on the draft.)")
    p.add_argument("--checkpoint-step", type=int, default=None,
                   help="checkpoint step to restore (default: latest)")
    p.add_argument("--tokenizer", default=None, metavar="NAME_OR_PATH",
                   help="transformers tokenizer enabling the text "
                        "surface: 'prompt' strings, stop STRINGS, "
                        "'text' in responses (ids-only without it)")
    p.add_argument("--register-with", default=None, metavar="URL",
                   help="router tier to self-register with "
                        "(http://host:port, workloads.router): this "
                        "replica heartbeats its address/model/"
                        "capacity + statz snapshot so the router can "
                        "load-balance and failover across the fleet")
    p.add_argument("--advertise", default=None, metavar="HOST:PORT",
                   help="address the ROUTER should dial back for this "
                        "replica (default 127.0.0.1:<port> — set to "
                        "the pod IP when router and replica are on "
                        "different hosts)")
    p.add_argument("--replica-id", default=None,
                   help="stable replica identity for routing/metrics "
                        "(default: the advertised address; keep it "
                        "stable across restarts so the router's "
                        "consistent-hash ring does not reshuffle)")
    p.add_argument("--register-interval", type=float, default=2.0,
                   help="seconds between router heartbeats (the "
                        "router's interval hint lowers it)")
    p.add_argument("--replica-role",
                   choices=["mixed", "prefill", "decode"],
                   default="mixed",
                   help="disaggregated-serving role, advertised via "
                        "/register and /statz: the router sends "
                        "prefill-heavy admissions to prefill-class "
                        "replicas and migrates the finished KV state "
                        "to decode-class ones (POST /migrate); "
                        "prefill/decode need --kv-paging (migration "
                        "is the paged pool's preempt/resume).  mixed "
                        "(default) serves everything locally")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    args = p.parse_args(argv)
    if args.int4 and args.quantized:
        p.error("--quantized and --int4 are mutually exclusive")
    if args.spec_ngram < 0:
        p.error("--spec-ngram must be >= 1 (0 disables)")
    if args.draft_config and args.spec_ngram:
        # before the (potentially many-GB) target build, like the
        # quantization check above
        p.error("--draft-config and --spec-ngram are mutually "
                "exclusive")
    if args.jump_len < 1:
        p.error("--jump-len must be >= 1")
    if args.prefix_chunk < 0:
        p.error("--prefix-chunk must be >= 0 (0 = auto)")
    if args.prefix_chunk and args.max_len % args.prefix_chunk:
        p.error(f"--prefix-chunk {args.prefix_chunk} must divide "
                f"--max-len {args.max_len}")
    if args.prefill_chunks < 1:
        p.error("--prefill-chunks must be >= 1")
    if args.max_pack < 2:
        p.error("--max-pack must be >= 2")
    if args.schedule_watchdog < 0:
        p.error("--schedule-watchdog must be >= 0 (0 disables)")
    if args.checkpoint_step is not None and not args.checkpoint:
        p.error("--checkpoint-step needs --checkpoint (without it the "
                "server would silently serve random weights)")
    if (args.kv_page_size or args.kv_pages or args.kv_dtype) \
            and not args.kv_paging:
        p.error("--kv-page-size/--kv-pages/--kv-dtype need --kv-paging")
    if args.kv_page_size < 0 or args.kv_pages < 0:
        p.error("--kv-page-size/--kv-pages must be >= 0")
    if args.prefix_registry_max < 1:
        p.error("--prefix-registry-max must be >= 1")
    if args.session_tier and not args.kv_paging:
        p.error("--session-tier needs --kv-paging (tier transitions "
                "are the paged pool's preempt/resume checkpoints)")
    if not args.session_tier and (
            args.session_dir or args.session_host_mb != 256
            or args.session_disk_keep != 512
            or args.session_idle != 30.0
            or args.session_host_idle != 120.0
            or args.session_seed != 0):
        p.error("--session-dir/--session-host-mb/--session-disk-keep/"
                "--session-idle/--session-host-idle/--session-seed "
                "need --session-tier")
    if args.session_tier:
        if args.session_host_mb < 1:
            p.error("--session-host-mb must be >= 1")
        if args.session_disk_keep < 1:
            p.error("--session-disk-keep must be >= 1")
        if args.session_idle <= 0 or args.session_host_idle <= 0:
            p.error("--session-idle/--session-host-idle must be > 0")
    if (args.advertise or args.replica_id) and not args.register_with:
        p.error("--advertise/--replica-id need --register-with")
    if args.replica_role != "mixed" and not args.kv_paging:
        p.error(f"--replica-role {args.replica_role} needs "
                "--kv-paging (KV migration is the paged pool's "
                "preempt/resume)")
    if args.register_interval <= 0:
        p.error("--register-interval must be > 0")
    try:
        tenant_quotas = parse_tenant_quotas(args.tenant_quota)
    except ValueError as e:
        p.error(str(e))
    slo_policies = None
    if args.slo:
        try:
            slo_policies = obs.parse_slo_specs(args.slo)
        except ValueError as e:
            p.error(str(e))
    if args.slo_window <= 0:
        p.error("--slo-window must be > 0")
    alert_rules = None
    if args.alert_rules:
        try:
            alert_rules = obs.load_alert_rules(args.alert_rules)
        except (OSError, ValueError) as e:
            p.error(f"--alert-rules: {e}")
    if args.alert_interval <= 0:
        p.error("--alert-interval must be > 0")
    if args.alert_window_scale <= 0:
        p.error("--alert-window-scale must be > 0")
    if args.flight_dump_keep < 1:
        p.error("--flight-dump-keep must be >= 1")
    import os as _pd_os
    profile_dir = (args.profile_dir
                   or _pd_os.environ.get("TPU_DP_PROFILE_DIR"))
    incident_dir = (args.incident_dir
                    or _pd_os.environ.get("TPU_DP_INCIDENT_DIR"))
    if args.profiler_hz <= 0:
        p.error("--profiler-hz must be > 0")

    # the persistent compile cache must be configured BEFORE the first
    # jit (param build included) or early executables miss it
    import os as _cc_os
    cache_dir = (args.compile_cache_dir
                 or _cc_os.environ.get("TPU_DP_COMPILE_CACHE_DIR"))
    if cache_dir:
        enable_compile_cache(cache_dir)

    quantized = "int4" if args.int4 else args.quantized
    mesh = None
    if args.tp > 1:
        # validate BEFORE the (potentially many-GB) param build: a bad
        # --tp must fail in milliseconds with an argparse error, not
        # after minutes of weight materialization
        import jax

        from .bench_serving import CONFIGS as _cfgs
        from .transformer import make_lm_mesh

        devs = jax.devices()
        if len(devs) < args.tp:
            p.error(f"--tp {args.tp} needs {args.tp} devices, "
                    f"found {len(devs)}")
        cfg0 = _cfgs[args.config]
        n_kv = getattr(cfg0, "n_kv_heads", None) or cfg0.n_heads
        if n_kv % args.tp:
            p.error(f"--tp {args.tp} must divide the config's "
                    f"{n_kv} KV heads (the cache shards on them)")
        mesh = make_lm_mesh(devs[:args.tp], seq=1, model=args.tp,
                            expert=1)
    if args.checkpoint:
        from .bench_serving import load_checkpoint_params

        try:
            cfg, model, params = load_checkpoint_params(
                args.config, args.max_len, quantized,
                args.checkpoint, step=args.checkpoint_step, mesh=mesh)
        except FileNotFoundError as e:
            p.error(str(e))
    else:
        cfg, model, params = build_model_and_params(
            args.config, args.max_len, quantized, mesh=mesh)
    draft = None
    if args.draft_config:
        # speculative serving (vLLM's --speculative-model): the draft
        # shares the target's vocab family; greedy requests decode in
        # spec rounds, sampled ones flip the scheduler to run_scan
        _, dmodel, dparams = build_model_and_params(
            args.draft_config, args.max_len, quantized, mesh=mesh)
        draft = (dmodel, dparams)
    elif args.spec_ngram:
        draft = "ngram"
    engine = ServingEngine(model, params, n_slots=args.n_slots,
                           eos_id=getattr(cfg, "eos_id", None),
                           prefix_chunk=(args.prefix_chunk or "auto"),
                           mesh=mesh, logprobs_k=args.logprobs_k,
                           draft=draft, gamma=args.gamma,
                           ngram_n=args.spec_ngram or 3,
                           jump_len=args.jump_len,
                           kv_paging=args.kv_paging,
                           kv_pages=args.kv_pages or None,
                           kv_page_size=args.kv_page_size,
                           kv_dtype=args.kv_dtype,
                           prefix_registry_max=args.prefix_registry_max,
                           fused_decode=args.fused_decode)
    tokenizer = None
    if args.tokenizer:
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(args.tokenizer)
        except Exception as e:
            p.error(f"could not load tokenizer {args.tokenizer!r}: {e}")
    srv = EngineServer(engine, max_new_tokens=args.max_new_tokens,
                       window=args.window, tokenizer=tokenizer,
                       max_grammars=args.max_grammars,
                       max_grammar_states=args.max_grammar_states,
                       max_queue=args.max_queue,
                       max_connections=args.max_connections,
                       client_timeout=args.client_timeout,
                       flight_record_dir=args.flight_record_dir,
                       flight_record_capacity=args.flight_record_capacity,
                       interleave=not args.no_interleave,
                       prefill_chunks=args.prefill_chunks,
                       schedule_watchdog_s=args.schedule_watchdog,
                       tenant_quotas=tenant_quotas,
                       packed_prefill=args.packed_prefill,
                       overlap_dispatch=args.overlap_dispatch,
                       max_pack=args.max_pack,
                       slo_policies=slo_policies,
                       slo_window_s=args.slo_window,
                       profile_dir=profile_dir,
                       flight_dump_keep=args.flight_dump_keep,
                       replica_role=args.replica_role,
                       alert_rules=alert_rules,
                       alert_interval_s=args.alert_interval,
                       alert_window_scale=args.alert_window_scale,
                       incident_dir=incident_dir,
                       profiler_hz=args.profiler_hz,
                       session_tier=args.session_tier,
                       session_dir=args.session_dir,
                       session_host_mb=args.session_host_mb,
                       session_disk_keep=args.session_disk_keep,
                       session_idle_s=args.session_idle,
                       session_host_idle_s=args.session_host_idle,
                       session_seed=args.session_seed)
    if args.fault_spec is not None or args.fault_seed is not None:
        if args.fault_spec is None:
            p.error("--fault-seed needs --fault-spec")
        import os as _os
        seed = (args.fault_seed if args.fault_seed is not None
                else int(_os.environ.get(faults.ENV_FAULT_SEED, "0")
                         or 0))
        faults.install(args.fault_spec, seed=seed,
                       recorder=srv.recorder)
    else:
        faults.install_from_env(recorder=srv.recorder)
    # pre-compile the adaptive-window scan variants + packed-prefill
    # shapes before taking traffic (each is its own XLA compile; see
    # warm_scheduler) — with a warm --compile-cache-dir this is a
    # cache load, and the printed number is the cold-start bench's
    # warm-vs-cold evidence
    t_warm = time.perf_counter()
    srv.warm_scheduler()
    print(f"warmup {time.perf_counter() - t_warm:.2f}s "
          f"(compile-cache: {cache_dir or 'off'})", flush=True)
    srv.start(host=args.host, port=args.port)
    if args.register_with:
        srv.start_registration(
            args.register_with, advertise=args.advertise,
            replica_id=args.replica_id, model=args.config,
            interval_s=args.register_interval)
    print(f"serving {args.config} (quantized={quantized}) on "
          f"http://{args.host}:{srv.port}  "
          f"[POST /generate, POST /v1/completions, GET /healthz, "
          f"GET /stats, GET /metrics]", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
