# tpulint: deterministic-path -- the engine equivalence suites replay this file's decisions from seeds; D1 bans bare random/time.time() here
"""Slot-based continuous batching on the KV-cache decode engine.

What vLLM does for the reference's serving example
(/root/reference/example/vllm-serve/deployment.yaml:28-56 — continuous
batching is the feature the image is deployed FOR), built natively on
``inference.DecodeTransformerLM``.  TPU-shaped: there is exactly ONE
compiled decode step for the whole engine lifetime — a fixed
``n_slots``-wide batch whose per-slot cache depths live in the
``cache_lens [S]`` vector — and request churn never recompiles
anything.  Admission costs one prefill (chunked for long prompts) plus
a pure-data cache insert.

Mechanics:

* **slots**: the engine owns a ``[S, T_max, Hkv, Dh]`` cache per layer.
  A request occupies one slot from admit to completion; free slots keep
  decoding garbage that nothing reads (static shapes beat conditional
  compute on TPU — masking, not branching).
* **admit**: the prompt prefills on a B=1 cache — in one shot, or in
  fixed-size chunks through the banded *extend* mode
  (``CachedBlock`` with ``decode=True, T>1``) so peak prefill
  attention memory is O(chunk · T_max) regardless of prompt length —
  then the filled rows are spliced into the slot with
  ``dynamic_update_slice`` and the slot's ``cache_lens`` entry is set
  to the true prompt length (chunk padding garbage sits beyond it and
  is overwritten by subsequent decode appends).
* **step**: one decode step for all S slots at their own depths;
  the host keeps per-slot bookkeeping (active, emitted tokens, EOS)
  and harvests only active slots' tokens.
* **stop handling**: a slot finishes on its stop token or its token
  budget; it is freed immediately and can be re-admitted into on the
  same engine without recompilation.

The per-slot depth machinery (vmapped appends + banded masks) is in
inference.py; this module is the scheduler around it.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .inference import (
    DecodeTransformerLM,
    dequantize_kv_rows,
    extend_step,
    init_cache,
    init_pool_cache,
    quantize_kv_rows,
    scan_boundary_update,
    validate_top_k,
)
from .kv_pool import PagePool, PagePoolExhausted

# Upper bound for the auto-selected prefill chunk.  128 rides the MXU
# tile (128 lanes) and keeps peak prefill attention memory at
# O(128 · T_max) regardless of prompt length; the resolved chunk is
# always a divisor of max_len so padded admission can never overflow
# the cache (see _resolve_chunk).
DEFAULT_CHUNK = 128

# Default admission grid when prefix caching is on (prefix_chunk=
# "auto"): APC matches floor to whole chunks, so the grid bounds how
# much of a repeated prompt is reusable — on the 128 grid a 128-token
# prompt floors every match to ZERO ((t_p - 1) // 128 == 0) and repeat
# prompts pay full prefills.  32 keeps matches fine-grained while the
# per-chunk extend still amortizes dispatch; it is the chunk the
# serving bench measured the front-door win with (BASELINE §ROUND-6),
# now the engine default instead of a harness-side trick.
PREFIX_CHUNK = 32

# Fused decode loop: the per-slot stop-id matrix rides the scan as a
# [S, K] operand, so K is part of the jit cache key — quantizing it to
# multiples of 4 bounds the compiled-variant count at a handful (most
# requests carry 0-4 stop ids) instead of one variant per distinct
# widest-stop-set size.
_STOP_PAD = 4

# Budget sentinel for the fused boundary carry when the engine has no
# max_new_tokens: far above any emitted0 + n_steps reachable within
# max_len, so the length comparison never fires.
_NO_BUDGET = 1 << 30


def _resolve_chunk(max_len: int,
                   cap: int = DEFAULT_CHUNK) -> Optional[int]:
    """Pick the admission chunk for ``chunk="auto"``: the largest
    divisor of *max_len* that is <= min(cap, max_len // 2).  A divisor
    guarantees ceil(t_p / c) * c <= max_len, so a prompt that passes
    the budget check is never rejected by chunk padding; the
    max_len // 2 cap leaves room for suffix extends after an unaligned
    explicit prefix.  Falls back to None (per-length compiles) for
    pathological max_len with no divisor >= 8."""
    c = min(cap, max(1, max_len // 2))
    while c > 1 and max_len % c:
        c -= 1
    return c if c >= 8 else None


@functools.partial(jax.jit, donate_argnums=(0,))
def _splice_slot(cache, mini, slot):
    """Write the B=1 *mini* cache into row *slot* of the engine cache.
    Pure data movement — per-layer dynamic_update_slice on the k/v
    buffers plus a scatter into cache_lens."""
    out = {}
    for layer, buf in cache.items():
        mini_l = mini[layer]
        out[layer] = {
            "cached_k": lax.dynamic_update_slice(
                buf["cached_k"], mini_l["cached_k"], (slot, 0, 0, 0)),
            "cached_v": lax.dynamic_update_slice(
                buf["cached_v"], mini_l["cached_v"], (slot, 0, 0, 0)),
            "cache_lens": lax.dynamic_update_slice(
                buf["cache_lens"], mini_l["cache_lens"], (slot,)),
        }
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _rollback_active(cache, lens, active):
    """Set cache_lens to the [S] vector *lens* where *active*, keeping
    the device value elsewhere — the batched rollback a speculative
    round ends with (rejected proposal rows become dead rows the next
    append overwrites).  Inactive slots MUST keep their own device
    lens: a released slot's host mirror is 0 while its device lens
    stays high, and lowering it would park subsequent clamped writes
    on top of the slot's prompt K/V — the APC donor rows release()
    promises stay valid."""
    lens = jnp.asarray(lens, jnp.int32)
    active = jnp.asarray(active)
    out = {}
    for layer, buf in cache.items():
        out[layer] = dict(buf)
        out[layer]["cache_lens"] = jnp.where(
            active, lens, buf["cache_lens"])
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _set_len(cache, slot, value):
    out = {}
    for layer, buf in cache.items():
        out[layer] = dict(buf)
        out[layer]["cache_lens"] = buf["cache_lens"].at[slot].set(value)
    return out


@jax.jit
def _slot_to_mini(cache, slot):
    """Copy row *slot* of the engine cache out as a B=1 mini cache
    (the inverse of _splice_slot's write).  NOT donated — the engine
    cache must survive; this is the data movement that makes a
    resident slot's prompt K/V reusable as an automatic prefix."""
    out = {}
    for layer, buf in cache.items():
        _, T, H, D = buf["cached_k"].shape
        out[layer] = {
            "cached_k": lax.dynamic_slice(
                buf["cached_k"], (slot, 0, 0, 0), (1, T, H, D)),
            "cached_v": lax.dynamic_slice(
                buf["cached_v"], (slot, 0, 0, 0), (1, T, H, D)),
            "cache_lens": lax.dynamic_slice(
                buf["cache_lens"], (slot,), (1,)),
        }
    return out


# -- paged-pool device helpers (kv_pool.PagePool makes the decisions;
# these move the bytes; one compiled variant each per pool shape) ------


@functools.partial(jax.jit, donate_argnums=(0,))
def _paged_splice(cache, mini, targets, slot, new_len):
    """Scatter a contiguous B=1 *mini* cache into pool pages.
    *targets* [n_tables] holds the physical page per logical page —
    SCRATCH for entries the slot does not own (shared prefix pages,
    unmapped tail), so their mini rows land in the garbage page
    instead of corrupting a neighbor.  Also sets cache_lens[slot].
    Quantized pools quantize on the way in."""
    out = {}
    for layer, buf in cache.items():
        m = mini[layer]
        ps = buf["cached_k"].shape[1]
        nt = targets.shape[0]
        n_kv, hd = buf["cached_k"].shape[2], buf["cached_k"].shape[3]
        mk = m["cached_k"][0].reshape(nt, ps, n_kv, hd)
        mv = m["cached_v"][0].reshape(nt, ps, n_kv, hd)
        o = dict(buf)
        if "k_scale" in buf:
            kq, ks = quantize_kv_rows(mk)
            vq, vs = quantize_kv_rows(mv)
            o["cached_k"] = buf["cached_k"].at[targets].set(kq)
            o["cached_v"] = buf["cached_v"].at[targets].set(vq)
            o["k_scale"] = buf["k_scale"].at[targets].set(ks)
            o["v_scale"] = buf["v_scale"].at[targets].set(vs)
        else:
            o["cached_k"] = buf["cached_k"].at[targets].set(
                mk.astype(buf["cached_k"].dtype))
            o["cached_v"] = buf["cached_v"].at[targets].set(
                mv.astype(buf["cached_v"].dtype))
        o["cache_lens"] = buf["cache_lens"].at[slot].set(new_len)
        out[layer] = o
    return out


@functools.partial(jax.jit, static_argnums=(2,))
def _paged_gather_mini(cache, table_row, dtype):
    """Gather one slot's pool pages back into a contiguous B=1 mini
    cache (the paged analog of _slot_to_mini — what seeds a suffix
    extend or a donor copy).  NOT donated: the pool must survive.
    Quantized pools dequantize on the way out (exact for rows that
    round-tripped through the same scales).  cache_lens is a zero the
    caller overwrites via _set_len."""
    out = {}
    for layer, buf in cache.items():
        ps = buf["cached_k"].shape[1]
        nt = table_row.shape[0]
        k = buf["cached_k"][table_row]   # [nt, ps, n_kv, hd]
        v = buf["cached_v"][table_row]
        if "k_scale" in buf:
            k = dequantize_kv_rows(k, buf["k_scale"][table_row], dtype)
            v = dequantize_kv_rows(v, buf["v_scale"][table_row], dtype)
        n_kv, hd = k.shape[-2], k.shape[-1]
        out[layer] = {
            "cached_k": k.reshape(1, nt * ps, n_kv, hd),
            "cached_v": v.reshape(1, nt * ps, n_kv, hd),
            "cache_lens": jnp.zeros((1,), jnp.int32),
        }
    return out


@jax.jit
def _paged_gather_raw(cache, table_row):
    """One slot's pool pages in STORAGE form ([n_tables, page, ...],
    int8 + scales when quantized) — the exact-round-trip snapshot
    preemption checkpoints to host."""
    out = {}
    for layer, buf in cache.items():
        d = {"k": buf["cached_k"][table_row],
             "v": buf["cached_v"][table_row]}
        if "k_scale" in buf:
            d["ks"] = buf["k_scale"][table_row]
            d["vs"] = buf["v_scale"][table_row]
        out[layer] = d
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _paged_restore_raw(cache, raw, targets, slot, new_len):
    """Scatter a preemption snapshot back into freshly-allocated pages
    (*targets*, SCRATCH beyond the restored length) — the inverse of
    _paged_gather_raw, bit-exact storage either dtype."""
    out = {}
    for layer, buf in cache.items():
        r = raw[layer]
        o = dict(buf)
        o["cached_k"] = buf["cached_k"].at[targets].set(r["k"])
        o["cached_v"] = buf["cached_v"].at[targets].set(r["v"])
        if "k_scale" in buf:
            o["k_scale"] = buf["k_scale"].at[targets].set(r["ks"])
            o["v_scale"] = buf["v_scale"].at[targets].set(r["vs"])
        o["cache_lens"] = buf["cache_lens"].at[slot].set(new_len)
        out[layer] = o
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_page(cache, src, dst):
    """Physical page copy in every layer (k, v, scales) — the
    copy-on-write data movement behind kv_pool.PagePool.cow."""
    out = {}
    for layer, buf in cache.items():
        o = dict(buf)
        o["cached_k"] = buf["cached_k"].at[dst].set(
            buf["cached_k"][src])
        o["cached_v"] = buf["cached_v"].at[dst].set(
            buf["cached_v"][src])
        if "k_scale" in buf:
            o["k_scale"] = buf["k_scale"].at[dst].set(
                buf["k_scale"][src])
            o["v_scale"] = buf["v_scale"].at[dst].set(
                buf["v_scale"][src])
        out[layer] = o
    return out


@jax.jit
def _pack_minis(minis):
    """Stack K B=1 admission caches into ONE B=K cache (the ragged
    packed-prefill batch).  One compiled program per pack size K — the
    bounded shape set warm_packed pre-compiles — and one host dispatch
    where K per-leaf concatenations would each be their own.  No
    donation: a concat's output can never alias its inputs."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *minis)


@functools.partial(jax.jit, static_argnums=(1,))
def _unpack_minis(cache, k: int):
    """Split a packed B=K cache back into K B=1 minis (one dispatch,
    the inverse of :func:`_pack_minis`)."""
    return tuple(
        jax.tree_util.tree_map(
            lambda x: lax.slice_in_dim(x, i, i + 1, axis=0), cache)
        for i in range(k))


def _lcp(a: np.ndarray, b: np.ndarray) -> int:
    """Longest common prefix of two int token arrays."""
    L = min(len(a), len(b))
    if L == 0:
        return 0
    neq = a[:L] != b[:L]
    idx = int(np.argmax(neq))
    return L if not neq[idx] else idx


def _ngram_propose(seq: np.ndarray, n: int, g: int) -> np.ndarray:
    """Prompt-lookup proposals (vLLM's [ngram] speculative mode): find
    the LATEST earlier occurrence of the sequence's final *n*-gram and
    propose the *g* tokens that followed it.  Repetitive continuations
    (summarization, code edits, retrieval echoes) hit constantly; a
    miss proposes the last token repeated — proposals are free guesses,
    the target verify is ground truth either way."""
    L = len(seq)
    n = min(n, L - 1)
    out = np.full(g, seq[-1] if L else 0, np.int32)
    if n < 1:
        return out
    key = seq[L - n:]
    # vectorized scan (histories approach max_len on the hot path —
    # a per-position Python loop would put interpreted work in the
    # round): all windows vs the key in one comparison, latest match
    windows = np.lib.stride_tricks.sliding_window_view(seq[:L - 1], n)
    hits = np.flatnonzero((windows == key).all(axis=1))
    if len(hits):
        i = int(hits[-1])
        cont = seq[i + n:i + n + g]
        out[:len(cont)] = cont
    return out


def _knobs_live_vec(temps, topks, topps, minps, pres, freqs,
                    reps) -> np.ndarray:
    """[S] bool: which slots' sampling knobs are armed.  One snapshot
    of this at harvest entry replaces the per-step full-vector
    recomputation scan_harvest used to pay (O(n_steps × n_slots) of
    pure waste: between two harvest steps the only knob mutator is
    _finish, which zeroes exactly the finishing slot's knobs — so
    dropping that slot from the snapshot's armed set is equivalent to
    re-reading all seven vectors)."""
    return ((np.asarray(temps) != 0) | (np.asarray(topks) != 0)
            | (np.asarray(topps) < 1.0) | (np.asarray(minps) != 0)
            | (np.asarray(pres) != 0) | (np.asarray(freqs) != 0)
            | (np.asarray(reps) != 1.0))


def _knobs_live(temps, topks, topps, minps, pres, freqs, reps) -> bool:
    """True when any slot's sampling knobs are armed.  THE predicate
    the engine's key-stream accounting hangs on: _sample's greedy fast
    path, run_scan's sampled flag, and its per-step draw count must
    all agree, or step() and run_scan() leave different draw counters
    behind (the streams would diverge after a retirement).  Penalties
    arm it too: a penalized temp-0 request still needs the full pick
    (penalized argmax != plain argmax)."""
    return bool(_knobs_live_vec(temps, topks, topps, minps, pres,
                                freqs, reps).any())


@functools.partial(jax.jit, donate_argnums=(0,))
def _zero_count_row(counts, slot):
    """Reset one slot's output-token histogram (at admit)."""
    return counts.at[slot].set(0.0)


@functools.partial(jax.jit, donate_argnums=(0,))
def _bump_counts(counts, tokens):
    """counts[s, tokens[s]] += 1 for every slot (garbage rows of
    inactive/unpenalized slots are harmless — their penalty knobs are
    zero — and are reset at the slot's next PENALIZED admit)."""
    return counts.at[jnp.arange(counts.shape[0]), tokens].add(1.0)


@functools.partial(jax.jit, donate_argnums=(0,))
def _bump_one(counts, slot, token):
    """counts[slot, token] += 1 (the admit-time first token)."""
    return counts.at[slot, token].add(1.0)


def _apply_penalties(logits, pres, freqs, reps, counts, seen):
    """vLLM's penalty family on the RAW logits (before temperature).
    Repetition first (multiplicative, over tokens seen in the PROMPT
    or output: positive logits divide by r, negative multiply — r = 1
    is bit-exact off), then presence/frequency (additive, over the
    OUTPUT histogram only — 0 is bit-exact off)."""
    r = reps[:, None]
    seen_any = seen > 0
    logits = jnp.where(
        seen_any, jnp.where(logits > 0, logits / r, logits * r),
        logits)
    out_seen = (counts > 0).astype(jnp.float32)
    return logits - pres[:, None] * out_seen - freqs[:, None] * counts


@functools.partial(jax.jit, donate_argnums=(0,))
def _set_count_row(counts, slot, row):
    """Install a precomputed histogram row (the prompt histogram at a
    repetition-penalized admit — host bincount keeps admission free of
    per-prompt-length compiled scatters)."""
    return counts.at[slot].set(row)


@functools.partial(jax.jit, static_argnums=(11,))
def _pick_tokens(logits, temps, topks, topps, minps, pres, freqs,
                 reps, counts, seen, key, seeded=False,
                 seeds=None, seed_streams=None, seed_on=None,
                 seed_idx=None):
    """Per-slot sampling in one vectorized pass: [S, V] logits with
    per-slot temperature (0 = greedy), top-k (0 = unrestricted),
    top-p / nucleus (1.0 = unrestricted), min-p (0 = unrestricted),
    presence/frequency penalties over the per-slot output-token
    histogram *counts* (0 = none), and repetition penalty over the
    prompt+output histogram *seen* (1 = none).  The per-slot knobs are
    DATA,
    not shapes, so mixed greedy/sampled batches share the engine's one
    compiled step.  Gumbel-max sampling: argmax(logits/T + G) is a
    categorical draw from softmax(logits/T), and zeroing the noise
    where T == 0 recovers exact greedy.  One descending sort serves
    both filters: top-k thresholds at the k-th largest logit; top-p
    keeps the smallest prefix of the TEMPERATURE-SCALED distribution
    whose mass reaches p (a token survives when the mass strictly
    before it is < p — the argmax always survives, so greedy rows are
    untouched by any p).  min-p keeps tokens whose candidate
    probability is >= min_p times the argmax's (applied after
    top-k/top-p, vLLM's sequential semantics) — in logit space, within
    log(min_p) of the surviving max, so the argmax always survives."""
    S, V = logits.shape
    logits = _apply_penalties(
        logits.astype(jnp.float32), pres, freqs, reps, counts, seen)
    safe_t = jnp.where(temps > 0, temps, 1.0)
    scaled = logits / safe_t[:, None]
    rows = jnp.arange(S)
    # top-k threshold on the raw logits (temperature-invariant order)
    k_eff = jnp.where(topks > 0, topks, V)
    sorted_desc = -jnp.sort(-logits, axis=-1)
    kth = sorted_desc[rows, k_eff - 1]
    masked = jnp.where(logits >= kth[:, None], scaled, -jnp.inf)
    # nucleus AFTER top-k (the sequential vLLM/HF semantics): the
    # candidate distribution is the top-k prefix RENORMALIZED, and the
    # kept set is its smallest prefix whose mass reaches p.  Division
    # by the (positive) temperature preserves order, so the scaled
    # sorted logits derive from the one sort above.
    sorted_scaled = sorted_desc / safe_t[:, None]
    in_topk = jnp.arange(V)[None, :] < k_eff[:, None]
    sorted_masked = jnp.where(in_topk, sorted_scaled, -jnp.inf)
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    before = jnp.cumsum(probs_sorted, axis=-1) - probs_sorted
    keep = before < topps[:, None]          # [S, V], a top-k subset
    n_keep = jnp.maximum(jnp.sum(keep, axis=-1), 1)
    pth = sorted_scaled[rows, n_keep - 1]
    masked = jnp.where(scaled >= pth[:, None], masked, -jnp.inf)
    # min-p on the surviving candidates: threshold at
    # max + log(min_p) in (scaled) logit space; rows with min_p == 0
    # are left untouched (log of the epsilon-clamped 0 would otherwise
    # cut tokens ~88 nats below the max)
    mmax = jnp.max(masked, axis=-1, keepdims=True)
    thresh = mmax + jnp.log(jnp.maximum(minps, 1e-30))[:, None]
    masked = jnp.where(
        (minps[:, None] > 0) & (scaled < thresh), -jnp.inf, masked)
    gumbel = jax.random.gumbel(key, (S, V), jnp.float32)
    if seeded:
        # per-request seeds (vLLM's `seed`): a seeded slot draws from
        # its OWN chain — PRNGKey(seed) folded by stream (the n>1 copy
        # index: a SECOND fold level, so "seed s copy 1" never aliases
        # "seed s+1 copy 0") then by the slot's draw index — making
        # its tokens reproducible regardless of neighbors or admission
        # order.  Unseeded rows keep the engine stream.
        def row_noise(seed, stream, idx):
            k = jax.random.fold_in(jax.random.PRNGKey(seed), stream)
            return jax.random.gumbel(
                jax.random.fold_in(k, idx), (V,), jnp.float32)

        own = jax.vmap(row_noise)(seeds, seed_streams, seed_idx)
        gumbel = jnp.where(seed_on[:, None] > 0, own, gumbel)
    noised = masked + jnp.where(temps[:, None] > 0, gumbel, 0.0)
    return jnp.argmax(noised, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(2,))
def _top_logprobs(logits, chosen, k):
    """log-softmax stats for emitted tokens: ([S] chosen logprob,
    [S, k] top-k logprobs, [S, k] top-k token ids).  Raw-logit
    log-softmax (temperature-independent — what evaluators score)."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    top_lp, top_id = jax.lax.top_k(lp, k)
    chosen_lp = jnp.take_along_axis(lp, chosen[:, None], axis=-1)[:, 0]
    return chosen_lp, top_lp, top_id


@functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10),
    donate_argnums=(12,)
)
def _scan_decode(model, n_steps, sampled, lp_k, pen, rep, seeded,
                 biased, minned, grammared, fused, params, cache, last,
                 lens, temps, topks, topps, minps, pres, freqs, reps,
                 counts, seen, bias, min_mask, min_toks, emitted0,
                 gtable, gstate0,
                 seeds, seed_streams, seed_on, seed_base, adapter_ids,
                 rng, draws0, btables=None, stop_mat=None,
                 eos_vec=None, budget=None):
    """n_steps decode steps in one lax.scan.  The per-step sampling key
    is fold_in(rng, draws0 + i) — the same chain ``step`` consumes one
    link of per call, so scan and step-by-step emit identical streams.
    Greedy mode (sampled=False) skips the pick entirely.  With lp_k,
    per-step logprob stats ride the scan outputs; with pen, the
    penalty histogram rides the carry (compiled variants scale with
    the STATIC flags — a handful engine-wide, never per request).

    With *fused*, per-slot finish flags ride the carry too
    (inference.scan_boundary_update): the step index and reason of the
    first eos/stop/budget boundary each slot hits, detected on-device
    against *eos_vec*/*stop_mat*/*budget* — harvest then truncates from
    the returned arrays instead of re-scanning columns on the host.
    The token math is identical either way (the detector only watches
    the picked tokens), which is what makes fused windows byte-equal
    to unfused ones by construction."""

    def step_fn(carry, i):
        if fused:
            cache, tok, pos, cnt, sn, gs, fin, frs = carry
        else:
            cache, tok, pos, cnt, sn, gs = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None], pos[:, None], decode=True,
            adapter_ids=adapter_ids, block_tables=btables,
            mutable=["cache"],
        )
        lg = logits[:, -1, :]
        if biased:
            # per-request logit_bias (OpenAI semantics): a plain add
            # before the pick; unbiased rows carry zeros, so their
            # tokens are untouched whatever the neighbors request
            lg = lg + bias
        if minned:
            # min_tokens floor: eos/stop ids masked while the slot's
            # emitted count (pre-window + step index) is below it —
            # the gate is per-step data, so a mid-window crossing
            # lifts the mask exactly where step-by-step decoding would
            gate = ((emitted0 + i) < min_toks).astype(
                lg.dtype)[:, None]
            lg = lg + min_mask * gate
        if grammared:
            # grammar state rides the carry: ONE [S, V] row gather
            # serves both the allowed-token mask (reject entries are
            # -1 — the mask is derived, never stored: a separate f32
            # mask array would double the grammar's HBM footprint,
            # ~1.4 GB for a JSON grammar at a 128k vocab) and the
            # post-pick state advance below
            grow = gtable[jnp.maximum(gs, 0)]
            gon = (gs >= 0).astype(lg.dtype)[:, None]
            lg = lg + jnp.where(grow < 0, -1e9, 0.0).astype(
                lg.dtype) * gon
        if sampled:
            nxt = _pick_tokens(
                lg, temps, topks, topps, minps, pres, freqs, reps,
                cnt, sn, jax.random.fold_in(rng, draws0 + i),
                seeded, seeds, seed_streams, seed_on, seed_base + i,
            )
        else:
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        if lp_k:
            # logprob stats reflect logit_bias (OpenAI semantics: the
            # reported distribution is the one the pick used) but stay
            # independent of temperature/penalties, which evaluators
            # score around; unbiased rows are value-identical either way
            out = (nxt,) + _top_logprobs(lg, nxt, lp_k)
        else:
            out = (nxt,)
        # histograms read BEFORE this step's token lands in them
        # (same order as step(): sample, then bump)
        if pen:
            cnt = cnt.at[jnp.arange(cnt.shape[0]), nxt].add(1.0)
        if rep:
            sn = sn.at[jnp.arange(sn.shape[0]), nxt].add(1.0)
        if grammared:
            # advance via the row already gathered for the mask
            stepped = jnp.take_along_axis(
                grow, nxt[:, None], axis=1)[:, 0]
            gs = jnp.where(gs >= 0, stepped, gs)
        if fused:
            fin, frs = scan_boundary_update(
                fin, frs, nxt, i, eos_vec, stop_mat, emitted0, budget)
            return (mut["cache"], nxt, pos + 1, cnt, sn, gs,
                    fin, frs), out
        return (mut["cache"], nxt, pos + 1, cnt, sn, gs), out

    if fused:
        S = last.shape[0]
        fin0 = jnp.full((S,), -1, jnp.int32)
        frs0 = jnp.zeros((S,), jnp.int32)
        (cache, _, _, counts, seen, _, fin, frs), ys = lax.scan(
            step_fn,
            (cache, last, lens, counts, seen, gstate0, fin0, frs0),
            jnp.arange(n_steps)
        )
        return ys, cache, counts, seen, fin, frs
    (cache, _, _, counts, seen, _), ys = lax.scan(
        step_fn, (cache, last, lens, counts, seen, gstate0),
        jnp.arange(n_steps)
    )
    return ys, cache, counts, seen, None, None


class _PrefillJob:
    """One admission prefill, advanced one compiled extend at a time.
    Host-side state machine shared by EVERY prefill driver — the
    one-shot ``admit()``, the scheduler's serial ``admit_step``, and
    the ragged packed path (``admit_step_packed``).  Sharing is what
    lets packing guarantee byte-identical streams: a packed chunk runs
    the same operand build (``chunk_np``/``pos_np``) and the same
    post-extend bookkeeping (``absorb``) as a serial one; only the
    extend itself is batched, and a batched extend computes each row
    independently (per-row banded attention over the row's own cache),
    which the packed equivalence suite pins bit-for-bit.

    ``packable`` gates the batched path: fixed-chunk-grid jobs only (a
    chunk-None job is one variable-length extend), no prompt-logprob
    capture (plp rows ride the serial path), and no MoE FFN (expert
    capacity couples batch rows, so a packed extend is not sworn
    bit-equal to the B=1 one)."""

    __slots__ = ("eng", "mini", "toks", "start", "aid", "aid_vec", "n",
                 "c", "total", "i", "last", "plp_k", "plp_out",
                 "packable", "packed_used", "counted")

    def __init__(self, eng, mini, toks_np: np.ndarray, start: int,
                 adapter: int, plp_k: int, plp_out: Optional[list]):
        n = int(toks_np.shape[1])
        self.eng = eng
        self.mini = mini
        self.start = start
        self.aid = adapter
        self.aid_vec = eng._adapter_vec(adapter)
        self.n = n
        self.plp_k = plp_k
        self.plp_out = plp_out
        self.last = None           # extends never prefilled anything
        self.i = 0
        self.packed_used = False
        self.counted = False
        c = eng.chunk
        if c is None:
            # one compiled extend per distinct prompt length — fine
            # for benchmarks/tests; a chunked engine pins admission to
            # a single compiled shape
            self.c = n
            self.total = 1
            self.toks = toks_np
            self.packable = False
            return
        padded = ((n + c - 1) // c) * c
        if start + padded > eng.model.max_len:
            raise ValueError(
                f"padded prompt {start + padded} exceeds max_len "
                f"{eng.model.max_len} (shrink chunk or prompt)")
        # fixed-size chunks: every chunk reuses ONE compiled extend;
        # the tail chunk pads with zeros whose K/V land beyond the
        # true length (fixed by absorb's final cache_lens set) and
        # whose outputs are discarded
        self.toks = np.concatenate(
            [toks_np, np.zeros((1, padded - n), np.int32)], axis=1)
        self.c = c
        self.total = padded // c
        self.packable = (plp_k == 0 and eng.model.n_experts == 0)

    @property
    def remaining(self) -> int:
        return self.total - self.i

    def close(self) -> None:
        """Abandon the job (abort_admit; API parity with the old
        chunk generator)."""
        self.i = self.total

    # -- operand build + post-extend bookkeeping (shared verbatim by
    # the serial and packed paths) ---------------------------------------

    def chunk_np(self) -> np.ndarray:
        """Host tokens [1, c] for the NEXT chunk."""
        return self.toks[:, self.i * self.c:(self.i + 1) * self.c]

    def pos_np(self) -> np.ndarray:
        """Host positions [1, c] for the NEXT chunk."""
        return (np.arange(self.c, dtype=np.int32)
                + self.start + self.i * self.c)[None, :]

    def pad_rows(self) -> int:
        """Zero-pad rows in the NEXT chunk (tail-chunk grid padding —
        the packed path's waste accounting)."""
        lo, hi = self.i * self.c, (self.i + 1) * self.c
        return max(0, hi - max(self.n, lo))

    def charge(self) -> None:
        """Prefill-token accounting, once per job, at FIRST dispatch
        (a job aborted before any chunk never ran anything)."""
        if not self.counted:
            self.counted = True
            self.eng._prefill_tokens += self.n

    def absorb_logits(self, logits) -> None:
        """Post-extend bookkeeping for the chunk just run: *logits* is
        this job's [c, V] (or [n, V]) row block.  Tracks the last REAL
        token's logits row and captures plp stats.  The cache side
        lands separately via :meth:`attach_mini` — the packed path
        keeps the B=K cache resident across rounds and unpacks once."""
        i, c = self.i, self.c
        if self.plp_k:
            # row j of chunk i scores padded token i*c + j + 1; rows
            # past the prompt score zeros whose stats are discarded
            # host-side (prompt_logprobs assembly stops at t_p)
            tgt = np.zeros(c, np.int32)
            avail = self.toks.shape[1] - (i * c + 1)
            if avail > 0:
                m = min(c, avail)
                tgt[:m] = self.toks[0, i * c + 1:i * c + 1 + m]
            self.plp_out.append(
                _top_logprobs(logits, jnp.asarray(tgt), self.plp_k))
        off = self.n - 1 - i * c
        if 0 <= off < c:
            self.last = logits[off]
        self.i = i + 1

    def attach_mini(self, mini) -> None:
        """Adopt the cache that now holds every absorbed chunk; when
        the job just completed, pin cache_lens back to the true length
        (chunk padding inflated it — the padded rows' K/V sit beyond
        it and are overwritten by later appends)."""
        if self.remaining == 0 and self.eng.chunk is not None:
            mini = _set_len(mini, jnp.int32(0),
                            jnp.int32(self.start + self.n))
        self.mini = mini

    def step(self) -> None:
        """Advance ONE chunk, unpacked: a single B=1 compiled extend
        (async dispatch — the host returns before the device
        finishes)."""
        eng = self.eng
        self.charge()
        logits, mini = extend_step(
            eng.model, eng.params, self.mini,
            jnp.asarray(self.chunk_np()), jnp.asarray(self.pos_np()),
            self.aid_vec)
        self.absorb_logits(logits[0])
        self.attach_mini(mini)


class AdmitState:
    """One in-flight chunked admission (begin_admit → admit_step* →
    finish_admit).  Pure host-side carrier: the slot reservation, the
    validated request knobs, the B=1 mini cache being prefilled (via
    the chunk generator), and — after the finish dispatch — the
    first-token pick still on device.  ``admit()`` drives one of these
    end to end, so the split path and the one-shot path are the same
    ops in the same order (the bit-identical-outputs invariant)."""

    __slots__ = (
        "slot", "prompt_np", "prompt", "t_p", "aid", "stops",
        "temperature", "top_k", "top_p", "min_p", "presence_penalty",
        "frequency_penalty", "repetition_penalty", "seed",
        "seed_stream", "ignore_eos", "min_tokens", "lp_n", "plp_n",
        "logit_bias", "gstart", "canon", "auto_src", "gen", "result",
        "plp_dev", "chunks_total", "chunks_done", "pick", "pick_stats",
        "spliced", "inplace", "first_cached", "share_pages",
        "prefill_end",
    )

    def __init__(self):
        self.gen = None
        self.result = None
        self.auto_src = None
        self.plp_dev = []
        self.chunks_total = 0
        self.chunks_done = 0
        self.pick = None
        self.pick_stats = None
        self.spliced = False
        # exact-repeat fast paths: inplace = the donor IS the target
        # slot (admission is one cache_lens fix, no row copy);
        # first_cached = the donor's materialized greedy first token
        # (no pick, no sync — argmax of the same logits row)
        self.inplace = False
        self.first_cached = None
        # paged mode: physical pages this admission will map by
        # REFERENCE (the copy-on-write prefix share).  Refcounts are
        # taken at begin — the pin that keeps a donor's pages alive
        # however the donor slot churns before finish — and released
        # by abort or consumed by the finish-time mapping.
        self.share_pages = []
        # paged mode: rows [0, prefill_end) hold real prefill content
        # (shared prefix + chunk-padded suffix); the slot owns pages
        # from the shared boundary up to here, decode appends allocate
        # on demand past it
        self.prefill_end = 0

    @property
    def ready(self) -> bool:
        """All prefill chunks dispatched; finish_admit may run."""
        return self.gen is None and self.result is not None


class _ScanHandle:
    """One dispatched-but-unharvested run_scan window (scan_dispatch /
    scan_harvest).  Snapshots the dispatch-time slot view so mid-window
    admissions (finish_admit between dispatch and harvest) never leak
    into the window's bookkeeping: ``active`` is who was in the scan,
    ``skip`` collects slots spliced after dispatch (their lens / draw
    counters were set by finish_admit and must not be advanced for a
    window they sat out)."""

    __slots__ = ("ys", "n_steps", "sampled", "lp_k", "grammared",
                 "active", "skip", "fused", "fin", "frs")

    def __init__(self, ys, n_steps, sampled, lp_k, grammared, active,
                 fused=False, fin=None, frs=None):
        self.ys = ys
        self.n_steps = n_steps
        self.sampled = sampled
        self.lp_k = lp_k
        self.grammared = grammared
        self.active = active
        self.skip = set()
        # fused boundary carry (device futures until harvest): per-slot
        # first-finish step index (-1 = none) and reason code
        self.fused = fused
        self.fin = fin
        self.frs = frs


class ServingEngine:
    """Continuous-batching scheduler over one compiled decode step.

    >>> eng = ServingEngine(decoder_model, params, n_slots=8, eos_id=2)
    >>> s = eng.admit([5, 17, 99])       # returns a slot id
    >>> eng.step(); eng.step()           # decode all active slots
    >>> eng.finished(s), eng.output(s)
    """

    def __init__(
        self,
        model: DecodeTransformerLM,
        params,
        n_slots: int,
        eos_id: Optional[int] = None,
        chunk: Union[int, None, str] = "auto",
        prefix_chunk: Union[int, None, str] = "auto",
        max_new_tokens: Optional[int] = None,
        mesh=None,
        rng: Optional[jax.Array] = None,
        auto_prefix: bool = True,
        auto_prefix_min: int = 8,
        logprobs_k: int = 0,
        draft=None,
        gamma: int = 4,
        ngram_n: int = 3,
        grammar=None,
        jump_len: int = 8,
        kv_paging: bool = False,
        kv_pages: Optional[int] = None,
        kv_page_size: int = 0,
        kv_dtype: Optional[str] = None,
        prefix_registry_max: int = 256,
        fused_decode: bool = False,
    ):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if logprobs_k < 0:
            raise ValueError("logprobs_k must be >= 0")
        if chunk == "auto":
            # compile-safe default: every admission reuses ONE compiled
            # extend shape no matter how many distinct prompt lengths
            # arrive (real traffic has hundreds; per-length compiles
            # are a trap outside benchmarks).  ``prefix_chunk`` picks
            # the grid: APC matches floor to whole chunks, so the
            # admission chunk IS the prefix-reuse granularity — the
            # chunk-32 alignment the serving bench used to carry as a
            # harness-side trick is now the engine default ("auto").
            # An int pins the grid explicitly (must divide max_len so
            # chunk padding can never overflow the cache); None keeps
            # the coarse 128-cap grid (cold-prefill-heavy workloads
            # that never repeat prompts).
            if prefix_chunk is None:
                chunk = _resolve_chunk(model.max_len)
            elif prefix_chunk == "auto":
                chunk = (_resolve_chunk(model.max_len, cap=PREFIX_CHUNK)
                         or _resolve_chunk(model.max_len))
            elif isinstance(prefix_chunk, str):
                raise ValueError(
                    f"prefix_chunk must be an int, None, or 'auto', "
                    f"got {prefix_chunk!r}")
            else:
                if prefix_chunk < 1:
                    raise ValueError("prefix_chunk must be >= 1")
                if model.max_len % prefix_chunk:
                    raise ValueError(
                        f"prefix_chunk {prefix_chunk} must divide "
                        f"max_len {model.max_len} (a divisor is what "
                        "guarantees chunk padding never overflows the "
                        "cache)")
                chunk = prefix_chunk
        elif isinstance(chunk, str):
            raise ValueError(f"chunk must be an int, None, or 'auto', "
                             f"got {chunk!r}")
        elif prefix_chunk != "auto":
            raise ValueError(
                "pass chunk OR prefix_chunk, not both: an explicit "
                "chunk already pins the admission/APC grid")
        if chunk is not None and chunk < 1:
            raise ValueError("chunk must be >= 1 when set")
        self.model = model
        self.n_slots = n_slots
        self.eos_id = eos_id
        self.chunk = chunk
        self.max_new_tokens = max_new_tokens
        self.mesh = mesh
        if mesh is not None:
            # tensor-parallel serving (the native analog of the vLLM
            # example's --tensor-parallel-size): params take the
            # training side's Megatron splits on the mesh's ``model``
            # axis, the KV cache shards on its (grouped) head axis, and
            # XLA propagates those shardings through every extend —
            # the engine code is identical, the collectives are placed
            # by the partitioner (SURVEY.md §5 division of labor)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .transformer import lm_tree_shardings

            n_kv = model.n_kv_heads or model.n_heads
            if n_kv % mesh.shape.get("model", 1):
                raise ValueError(
                    f"n_kv_heads={n_kv} must divide the mesh's model "
                    f"axis ({mesh.shape.get('model', 1)}) to shard the "
                    "KV cache")
            params = jax.device_put(params, lm_tree_shardings(mesh, params))
            self._kv_sharding = NamedSharding(
                mesh, P(None, None, "model", None))
            self._len_sharding = NamedSharding(mesh, P())
        else:
            self._kv_sharding = None
            self._len_sharding = None
        self.params = params
        # -- paged KV pool (opt-in; contiguous stays the default and
        # bit-for-bit intact) ------------------------------------------------
        # Storage becomes a [P+1, page, Hkv, Dh] physical pool per
        # layer + a host-side free-list allocator with per-slot block
        # tables (kv_pool.PagePool).  APC admission maps shared
        # prefixes to SHARED read-only pages (refcounted,
        # copy-on-write on append) instead of copying donor rows, and
        # preemption can checkpoint a slot's pages to host and free
        # them under pressure.  Decode gathers the pool back into the
        # contiguous logical view inside the same compiled step, so
        # tokens are bit-identical to the contiguous engine (pinned by
        # the paged equivalence suite); int8 pool storage (kv_dtype)
        # is the one lossy opt-out.
        self._paged = bool(kv_paging)
        self._pool: Optional[PagePool] = None
        self._pmodel = None
        self._btables_dev = None
        self._kv_quant = False
        self._preempt_cb = None      # server-installed eviction policy
        self._kv_preemptions = 0
        self._prefix_evictions = 0
        self._park_seq = [0] * n_slots
        self._park_counter = 0
        if kv_paging:
            if chunk is None:
                raise ValueError(
                    "kv_paging needs a chunked engine (pass chunk or "
                    "prefix_chunk; paged splices land whole pages on "
                    "the admission grid)")
            ps = int(kv_page_size) or chunk
            if ps < 1:
                raise ValueError("kv_page_size must be >= 1")
            if model.max_len % ps:
                raise ValueError(
                    f"kv_page_size {ps} must divide max_len "
                    f"{model.max_len}")
            if chunk % ps:
                raise ValueError(
                    f"kv_page_size {ps} must divide the admission "
                    f"chunk {chunk}: APC matches floor to whole "
                    "chunks, and whole-page sharing needs the chunk "
                    "grid to lie on the page grid")
            if kv_dtype not in (None, "int8"):
                raise ValueError(
                    f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
            self._kv_quant = kv_dtype == "int8"
            n_tables = model.max_len // ps
            pages = (int(kv_pages) if kv_pages is not None
                     else n_slots * n_tables)
            self._pool = PagePool(pages, ps, n_slots, model.max_len)
            self._pmodel = model.clone(kv_page_size=ps,
                                       kv_quant=self._kv_quant)
            self.cache = self._place_pool_cache(
                init_pool_cache(model, n_slots, pages, ps,
                                self._kv_quant))
        else:
            self.cache = self._place_cache(init_cache(model, n_slots))
        if prefix_registry_max < 1:
            raise ValueError("prefix_registry_max must be >= 1")
        self.prefix_registry_max = prefix_registry_max
        self._prefix_touch: Dict[int, int] = {}  # handle -> use seq
        self._use_seq = 0
        self.lens = [0] * n_slots          # host mirror of cache_lens
        self.active = [False] * n_slots
        # slots held by an in-flight chunked admission (begin_admit
        # reserved them; finish_admit/abort_admit releases).  Reserved
        # slots are invisible to free_slots() but stay INACTIVE for
        # every decode path — the scan treats them exactly like any
        # parked slot until the splice lands
        self._reserved = [False] * n_slots
        # the one outstanding scan_dispatch handle (None when no
        # deferred-harvest window is open); finish_admit adds its slot
        # to the handle's skip set so harvest bookkeeping never
        # clobbers a mid-window splice
        self._inflight_scan = None
        self.last_token = np.zeros(n_slots, np.int32)
        self.outputs: List[List[int]] = [[] for _ in range(n_slots)]
        self._finished: Dict[int, List[int]] = {}
        self._finish_reason: Dict[int, str] = {}
        # per-request stop-token sets (vLLM's `stop_token_ids`):
        # host-side data consulted at harvest, never a recompile
        self._stops: List[frozenset] = [frozenset()] * n_slots
        # vLLM's ignore_eos (fixed-length benchmarking through the
        # real engine path: decode to the budget regardless of eos)
        self._ignore_eos = [False] * n_slots
        # per-request seeds (vLLM's `seed`): seeded slots draw from
        # their own fold_in chain, indexed by a PER-SLOT draw counter
        # — never the global one, which neighbors' admissions advance
        # (the whole point of a seed is a stream that ignores them)
        self.seeds = np.zeros(n_slots, np.uint32)
        self._seed_streams = np.zeros(n_slots, np.int32)
        self._seed_on = np.zeros(n_slots, np.int32)
        self._slot_draws = [0] * n_slots
        # logprobs: the engine computes top-`logprobs_k` stats for ALL
        # slots when enabled (one compiled variant, engine-wide k —
        # masking, not branching); requests ask for n <= k and the
        # host trims.  vLLM's `logprobs` API, compile-stable.
        self.logprobs_k = logprobs_k
        self._lp_want = [0] * n_slots
        self._lp_records: List[list] = [[] for _ in range(n_slots)]
        # prompt_logprobs records (vLLM's prompt-scoring API): filled
        # at admit from the prefill chunks' own logits
        self._prompt_lp: List[list] = [[] for _ in range(n_slots)]
        self._prefixes: Dict[int, tuple] = {}
        self._next_prefix = 0
        # automatic prefix caching (vLLM's APC, the feature the
        # reference's serving image ships by default): match new
        # prompts against resident slot prompts and the registry at
        # CHUNK granularity — reused rows sit on the same chunk grid
        # the cold path would prefill, so outputs stay bit-identical.
        # Unchunked engines disable it (no grid to stay exact on).
        self.auto_prefix = bool(auto_prefix) and chunk is not None
        self.auto_prefix_min = auto_prefix_min
        # per-slot resident prompt: (tokens, adapter, canon, last)
        # where canon is the prefix length up to which the slot's
        # cache rows lie on the chunk grid (decode appends never touch
        # them) and last is the admission's final-prompt-position
        # logits row ([V] device array) — what makes an EXACT repeat
        # prompt a zero-extend admission: splice the donor rows, reuse
        # the stored row (the same device value a cold admission
        # computes, so tokens stay bit-identical)
        self._slot_prompts: list = [None] * n_slots
        self._prefill_tokens = 0
        self._prefix_hits = 0
        self._prefix_reused_tokens = 0
        # ragged packed prefill accounting (admit_step_packed): batched
        # dispatches, chunk-rows they carried, distinct admissions that
        # rode them, and tail-chunk zero-pad rows they computed
        self._packed_extends = 0
        self._packed_rows = 0
        self._packed_requests = 0
        self._packed_pad_tokens = 0
        # sampling: per-slot temperature (0 = greedy) and top-k (0 =
        # unrestricted), set at admit; one key stream for the engine
        self._rng = jax.random.PRNGKey(0) if rng is None else rng
        self._draws = 0
        self._steps = 0
        self._tokens = 0
        self._completed = 0
        self.temps = np.zeros(n_slots, np.float32)
        self.topks = np.zeros(n_slots, np.int32)
        self.topps = np.ones(n_slots, np.float32)
        self.minps = np.zeros(n_slots, np.float32)
        self.pres = np.zeros(n_slots, np.float32)
        self.freqs = np.zeros(n_slots, np.float32)
        self.reps = np.ones(n_slots, np.float32)
        # device mirrors of the per-slot knob vectors, rebuilt only
        # when an admit/retire touches them: run_scan used to pay ~15
        # host->device conversions of unchanged arrays per window,
        # which at short windows was a measurable slice of the serving
        # hot path (None = stale, rebuilt on next scan)
        self._knob_cache = None
        # fused decode loop (opt-in): scan windows carry per-slot
        # finish flags on-device (eos / stop-set / remaining budget,
        # see _scan_decode), harvest truncates from the returned
        # flag/step-index arrays with columnar numpy instead of the
        # per-step per-slot Python walk, and the scheduler may
        # dispatch SAMPLED windows ahead (the boundary carry makes the
        # harvest's draw accounting independent of host knob churn
        # behind the dispatch).  Outputs are byte-identical to the
        # unfused paths by construction — the fused toggle matrix in
        # tests/test_scheduler.py pins it across every feature.
        self.fused_decode = bool(fused_decode)
        # device mirrors for the boundary detector (stop-id matrix +
        # effective per-slot eos vector), same rebuild-on-stale
        # lifecycle as _knob_cache but invalidated by stop/ignore_eos
        # churn, which knob-identical admissions can cause
        self._fused_cache = None
        self._fused_windows = 0
        self._fused_truncated = 0
        # output-token histogram for the penalties: [S, V] on device,
        # bumped per decode step only while some penalized request is
        # live, reset per slot at each PENALIZED admit (unpenalized
        # slots may hold stale rows — their zero knobs mask them)
        self._counts = jnp.zeros((n_slots, model.vocab), jnp.float32)
        # prompt+output histogram for the repetition penalty (vLLM
        # scopes it wider than presence/frequency), same lifecycle
        self._seen = jnp.zeros((n_slots, model.vocab), jnp.float32)
        self._zero_vocab_row = jnp.zeros((1, model.vocab), jnp.float32)
        # per-request logit_bias rows (OpenAI's logit_bias): applied as
        # a plain add before every pick; rows are zero unless the
        # slot's admit supplied a bias, and a stale row is re-zeroed at
        # the slot's next unbiased admit (host flag tracks staleness —
        # unlike the penalty histograms there is no knob masking a
        # stale row, the add is unconditional while any bias is live)
        self._bias = jnp.zeros((n_slots, model.vocab), jnp.float32)
        self._bias_on = [False] * n_slots
        # min_tokens (vLLM): a -1e6 mask over eos + the request's stop
        # ids, applied while the slot has emitted fewer than min_toks
        # tokens — the gate is computed from per-slot counters inside
        # every pick, so step, run_scan (mid-window crossings included),
        # and spec rounds stay token-identical.  A stale row is
        # harmless: min_toks resets to 0 at every admit, gating it off.
        # MAGNITUDE HIERARCHY: -1e6 floors beat any real logit or
        # [-100, 100] bias, but yield to the grammar's -1e9 — when a
        # grammar reaches an accepting state where ONLY eos continues,
        # eos (floored to -1e6) must still beat every grammar-rejected
        # token (-1e9), so the request retires IN-GRAMMAR below its
        # floor instead of degenerating to unmasked argmax.
        self._min_mask = jnp.zeros((n_slots, model.vocab), jnp.float32)
        self.min_toks = np.zeros(n_slots, np.int32)
        # grammar-constrained decoding (vLLM's guided decoding, the
        # TPU way): a REGISTRY of token-level DFAs (grammar.TokenDfa)
        # concatenated into ONE combined [N, V] int32 table with
        # per-grammar state offsets; the per-slot state rides the
        # decode scan's carry.  The logit mask is DERIVED in-step from
        # the table's reject entries (storing a parallel f32 mask
        # would double the grammar HBM footprint).  Requests opt in
        # with admit(grammar=<gid>) (True = grammar 0) and pay one
        # [S, V] row gather per step, inside the same compiled step as
        # everyone else.  gstate -1 = unconstrained.  The combined
        # table's CAPACITY doubles when a registration outgrows it —
        # one scan recompile per doubling, never per request (the
        # compile key is the table shape; see register_grammar).
        if jump_len < 1:
            raise ValueError("jump_len must be >= 1")
        self.jump_len = jump_len
        self._goffsets: List[int] = []
        # per-gid [row_start, row_end) in the combined table: the
        # translation KV-migration needs to re-home a checkpoint's
        # gstate onto another engine's table (registration order — and
        # with it the absolute offsets — differs across replicas)
        self._growbounds: List[Tuple[int, int]] = []
        self._gstates_used = 0
        self._gtable_np = None
        self._gtable = None
        self.gstate = np.full(n_slots, -1, np.int32)
        if grammar is not None:
            self.register_grammar(grammar)
        # per-slot LoRA adapter ids (-1 = base model); only consulted
        # when the model was built with n_adapters > 0
        self.adapters = np.full(n_slots, -1, np.int32)
        # engine-level speculative decoding (vLLM's speculative_model):
        # a small greedy draft proposes gamma tokens per round for EVERY
        # active slot (one batched lax.scan), the target verifies all of
        # them in ONE [S, gamma+1] extend — k in [1, gamma+1] tokens
        # commit per slot per round, with ONE host round-trip where
        # step() pays one per token.  Greedy-only (see spec_round).
        self._draft_model = self._draft_params = None
        self._draft_cache = None
        self._ngram = False
        self.ngram_n = ngram_n
        self.gamma = gamma
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._jump_rounds = 0
        self._jump_forced = 0
        if draft == "ngram":
            # draft-FREE speculation (vLLM's [ngram] / prompt-lookup
            # mode): proposals come from the request's own token
            # history on the host — no second model, no draft cache,
            # same batched verify
            if gamma < 1:
                raise ValueError("gamma must be >= 1")
            if ngram_n < 1:
                raise ValueError("ngram_n must be >= 1")
            self._ngram = True
        elif draft is not None:
            draft_model, draft_params = draft
            if gamma < 1:
                raise ValueError("gamma must be >= 1")
            if draft_model.vocab != model.vocab:
                raise ValueError(
                    f"draft vocab {draft_model.vocab} != target vocab "
                    f"{model.vocab}")
            if draft_model.max_len < model.max_len:
                raise ValueError(
                    f"draft max_len {draft_model.max_len} < target "
                    f"max_len {model.max_len} (the draft cache must "
                    "cover every committable position)")
            if mesh is not None:
                from .transformer import lm_tree_shardings as _lts

                n_kv_d = draft_model.n_kv_heads or draft_model.n_heads
                if n_kv_d % mesh.shape.get("model", 1):
                    raise ValueError(
                        f"draft n_kv_heads={n_kv_d} must divide the "
                        f"mesh's model axis")
                draft_params = jax.device_put(
                    draft_params, _lts(mesh, draft_params))
            self._draft_model = draft_model
            self._draft_params = draft_params
            self._draft_cache = self._place_cache(
                init_cache(draft_model, n_slots))

    def register_grammar(self, grammar) -> int:
        """Register a token-level DFA (``grammar.TokenDfa``); returns a
        grammar id for ``admit(grammar=gid)``.  All registered grammars
        share ONE combined ``[N, V]`` table/mask (each grammar's states
        offset into it), so the compiled decode step keys on the
        table's SHAPE, not the grammar count: capacity doubles when a
        registration outgrows it (one recompile per doubling — the
        vLLM-guided-decoding analog of compiling a new FSM once and
        caching it), and registrations within capacity are pure data.
        """
        if grammar.table.shape[1] != self.model.vocab:
            raise ValueError(
                f"grammar vocab {grammar.table.shape[1]} != model "
                f"vocab {self.model.vocab}")
        n_new = int(grammar.table.shape[0])
        off = self._gstates_used
        need = off + n_new
        cap = 0 if self._gtable_np is None else self._gtable_np.shape[0]
        if need > cap:
            new_cap = max(64, 1 << (need - 1).bit_length())
            # the table is the ONLY grammar array (the logit mask is
            # derived in-step from reject entries — a stored f32 mask
            # would double the HBM footprint, ~1.4 GB for a JSON
            # grammar at a 128k vocab), and it packs to int16 while
            # every state id fits (one more halving; growth past
            # 32767 states re-widens to int32 — a recompile, like any
            # capacity change).  Padding rows are unreachable (every
            # start state and transition stays inside a registered
            # grammar's rows).
            dt = np.int16 if new_cap <= 32767 else np.int32
            table = np.full((new_cap, self.model.vocab), -1, dt)
            if self._gtable_np is not None:
                table[:off] = self._gtable_np[:off]
            self._gtable_np = table
        # local state ids shift by this grammar's offset; rejects stay -1
        self._gtable_np[off:need] = np.where(
            np.asarray(grammar.table, np.int32) >= 0,
            np.asarray(grammar.table, np.int32) + np.int32(off),
            np.int32(-1)).astype(self._gtable_np.dtype)
        self._gstates_used = need
        self._goffsets.append(off + int(grammar.start))
        self._growbounds.append((off, need))
        # device mirror rebuilds on every registration (one [N, V]
        # host-to-device copy; same shape unless capacity grew)
        self._gtable = jnp.asarray(self._gtable_np)
        return len(self._goffsets) - 1

    @property
    def n_grammars(self) -> int:
        """How many grammars are registered (admit gids are
        ``range(n_grammars)``)."""
        return len(self._goffsets)

    def grammar_rel(self, gstate: int) -> int:
        """A combined-table state id -> the GRAMMAR-LOCAL row index
        (-1 stays -1).  This is the engine-portable form a migrated
        checkpoint carries: absolute offsets depend on THIS engine's
        registration order, local ids only on the grammar itself."""
        if gstate < 0:
            return -1
        for off, end in self._growbounds:
            if off <= gstate < end:
                return gstate - off
        raise ValueError(
            f"gstate {gstate} is in no registered grammar's rows")

    def grammar_abs(self, gid: int, rel: int) -> int:
        """Inverse of :meth:`grammar_rel` against THIS engine's table:
        grammar *gid*'s local state *rel* -> combined-table id."""
        if rel < 0:
            return -1
        off, end = self._growbounds[gid]
        if off + rel >= end:
            raise ValueError(
                f"local state {rel} outside grammar {gid}'s "
                f"{end - off} rows")
        return off + rel

    def _place_cache(self, cache):
        """Apply the TP shardings to a cache pytree (no-op meshless)."""
        if self._kv_sharding is None:
            return cache
        return {
            layer: {
                "cached_k": jax.device_put(buf["cached_k"],
                                           self._kv_sharding),
                "cached_v": jax.device_put(buf["cached_v"],
                                           self._kv_sharding),
                "cache_lens": jax.device_put(buf["cache_lens"],
                                             self._len_sharding),
            }
            for layer, buf in cache.items()
        }

    def _place_pool_cache(self, cache):
        """TP shardings for the paged pool (no-op meshless): pools
        shard on the KV-head axis like the contiguous cache; scales
        follow their pool's head axis."""
        if self._kv_sharding is None:
            return cache
        from jax.sharding import NamedSharding, PartitionSpec as P

        scale_s = NamedSharding(self.mesh, P(None, None, "model"))
        out = {}
        for layer, buf in cache.items():
            o = {
                "cached_k": jax.device_put(buf["cached_k"],
                                           self._kv_sharding),
                "cached_v": jax.device_put(buf["cached_v"],
                                           self._kv_sharding),
                "cache_lens": jax.device_put(buf["cache_lens"],
                                             self._len_sharding),
            }
            if "k_scale" in buf:
                o["k_scale"] = jax.device_put(buf["k_scale"], scale_s)
                o["v_scale"] = jax.device_put(buf["v_scale"], scale_s)
            out[layer] = o
        return out

    # -- paged-pool plumbing -----------------------------------------------

    @property
    def kv_paging(self) -> bool:
        return self._paged

    def _bt(self):
        """Device mirror of the pool's block tables, re-uploaded only
        when host-side mappings changed (same staleness discipline as
        the knob cache)."""
        assert self._pool is not None
        if self._btables_dev is None or self._pool.dirty:
            self._btables_dev = jnp.asarray(self._pool.tables)
            self._pool.dirty = False
        return self._btables_dev

    def set_preempt_cb(self, cb) -> None:
        """Install the server's preemption policy: ``cb(exclude_slot)
        -> bool`` must free pool pages (typically by preempting a
        lower-priority slot via :meth:`preempt`) and return whether it
        made progress.  The engine calls it only after reclaiming
        parked donor pages failed to satisfy an allocation."""
        self._preempt_cb = cb

    def _alloc_page(self) -> int:
        assert self._pool is not None
        while True:
            try:
                return self._pool.alloc()
            except PagePoolExhausted:
                if self._reclaim_parked():
                    continue
                if (self._preempt_cb is not None
                        and self._preempt_cb(-1)):
                    continue
                raise

    def _reclaim_parked(self) -> bool:
        """Evict the least-recently-parked donor record whose pages
        only the record pins — the bounded answer to
        release-survives-forever donor rows under pool pressure."""
        assert self._pool is not None
        best = None
        for s in range(self.n_slots):
            if (self.active[s] or self._reserved[s]
                    or self._slot_prompts[s] is None
                    or not self._pool.mapped(s)):
                continue
            if best is None or self._park_seq[s] < self._park_seq[best]:
                best = s
        if best is None:
            return False
        self._drop_donor(best)
        return True

    def _drop_donor(self, slot: int) -> None:
        assert self._pool is not None
        self._pool.clear_slot(slot)
        self._slot_prompts[slot] = None
        self._prefix_evictions += 1

    def _make_writable(self, slot: int, idx: int) -> None:
        """Guarantee (slot, idx) maps a page this slot may append
        into: map a fresh page, or copy-on-write a shared one."""
        pool = self._pool
        assert pool is not None
        e = pool.entry(slot, idx)
        if e == pool.scratch:
            pool.map(slot, idx, self._alloc_page())
        elif not pool.writable(slot, idx):
            new = self._alloc_page()
            self.cache = _copy_page(self.cache, jnp.int32(e),
                                    jnp.int32(new))
            pool.cow(slot, idx, new)

    def _ensure_append_pages(self, n_new: int) -> None:
        """Pre-dispatch page budget: every ACTIVE slot gets writable
        pages covering its next *n_new* appends (fresh allocations
        past the prefill, CoW where a shared prefix page is about to
        be written).  Runs on the host before the decode dispatch;
        allocation failure escalates reclaim → preemption callback →
        PagePoolExhausted."""
        if not self._paged:
            return
        assert self._pool is not None
        for s in range(self.n_slots):
            if not self.active[s]:
                continue
            start = self.lens[s]
            if start >= self.model.max_len:
                continue
            end = min(start + n_new, self.model.max_len)
            for idx in self._pool.pages_for(start, end):
                if not self.active[s]:
                    break  # the preemption policy evicted this slot
                self._make_writable(s, idx)

    def preempt(self, slot: int) -> Dict[str, object]:
        """Preemption-by-page-eviction: checkpoint an ACTIVE slot's KV
        pages to host (storage-exact — int8 pools round-trip their raw
        bytes + scales), free the pages, and return an opaque state
        :meth:`resume` re-admits from.  Host bookkeeping (outputs,
        knobs, draw chains, grammar state) rides the state; penalty
        histograms are rebuilt from token counts at resume, which
        reproduces the device values exactly (unit float increments).
        Seeded/greedy/grammar streams continue bit-identically after
        resume; unseeded sampled streams keep the documented
        global-stream caveat."""
        if not self._paged:
            raise RuntimeError("preemption needs kv_paging=True")
        assert self._pool is not None
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        row = jnp.asarray(self._pool.tables[slot])
        raw = jax.device_get(_paged_gather_raw(self.cache, row))
        state: Dict[str, object] = {
            "kv": raw,
            "lens": int(self.lens[slot]),
            "outputs": list(self.outputs[slot]),
            "last_token": int(self.last_token[slot]),
            "record": self._slot_prompts[slot],
            "stops": self._stops[slot],
            "ignore_eos": self._ignore_eos[slot],
            "temperature": float(self.temps[slot]),
            "top_k": int(self.topks[slot]),
            "top_p": float(self.topps[slot]),
            "min_p": float(self.minps[slot]),
            "presence_penalty": float(self.pres[slot]),
            "frequency_penalty": float(self.freqs[slot]),
            "repetition_penalty": float(self.reps[slot]),
            "adapter": int(self.adapters[slot]),
            "seed": int(self.seeds[slot]),
            "seed_stream": int(self._seed_streams[slot]),
            "seed_on": int(self._seed_on[slot]),
            "slot_draws": int(self._slot_draws[slot]),
            "lp_want": int(self._lp_want[slot]),
            "lp_records": list(self._lp_records[slot]),
            "prompt_lp": list(self._prompt_lp[slot]),
            "min_toks": int(self.min_toks[slot]),
            "gstate": int(self.gstate[slot]),
            "bias": (np.asarray(self._bias[slot])
                     if self._bias_on[slot] else None),
        }
        self.active[slot] = False
        self._pool.clear_slot(slot)
        self._slot_prompts[slot] = None
        self.lens[slot] = 0
        self._reset_slot_params(slot)
        self._kv_preemptions += 1
        if self._inflight_scan is not None:
            # a window dispatched before the preemption must not
            # advance host mirrors the resume will overwrite
            self._inflight_scan.skip.add(slot)
        return state

    def resume(self, state: Dict[str, object]) -> int:
        """Re-admit a :meth:`preempt` checkpoint into a free slot:
        allocate pages, scatter the raw snapshot back, and restore
        every host mirror.  Raises RuntimeError (no free slot) or
        PagePoolExhausted (still under pressure) — the caller
        re-queues and retries later."""
        if not self._paged:
            raise RuntimeError("preemption needs kv_paging=True")
        pool = self._pool
        assert pool is not None
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slots")
        slot = free[0]
        lens = int(state["lens"])  # type: ignore[arg-type]
        if self._slot_prompts[slot] is not None:
            self._drop_donor(slot)
        pool.clear_slot(slot)
        n_pages = pool.pages_needed(lens)
        got: List[int] = []
        try:
            for _ in range(n_pages):
                # reclaim parked donor pages (but never preempt — the
                # resuming request is itself the yielding party) before
                # giving up
                while True:
                    try:
                        got.append(pool.alloc())
                        break
                    except PagePoolExhausted:
                        if not self._reclaim_parked():
                            raise
        except PagePoolExhausted:
            for p in got:
                pool.give_back(p)
            raise
        targets = np.full(pool.n_tables, pool.scratch, np.int32)
        for idx, p in enumerate(got):
            pool.map(slot, idx, p)
            targets[idx] = p
        self.cache = _paged_restore_raw(
            self.cache, state["kv"], jnp.asarray(targets),
            jnp.int32(slot), jnp.int32(lens))
        self.lens[slot] = lens
        self.outputs[slot] = list(state["outputs"])  # type: ignore[arg-type]
        self.last_token[slot] = state["last_token"]
        self._slot_prompts[slot] = state["record"]
        self._stops[slot] = state["stops"]
        self._ignore_eos[slot] = state["ignore_eos"]
        self.temps[slot] = state["temperature"]
        self.topks[slot] = state["top_k"]
        self.topps[slot] = state["top_p"]
        self.minps[slot] = state["min_p"]
        self.pres[slot] = state["presence_penalty"]
        self.freqs[slot] = state["frequency_penalty"]
        self.reps[slot] = state["repetition_penalty"]
        self.adapters[slot] = state["adapter"]
        self.seeds[slot] = np.uint32(state["seed"])
        self._seed_streams[slot] = state["seed_stream"]
        self._seed_on[slot] = state["seed_on"]
        self._slot_draws[slot] = int(state["slot_draws"])  # type: ignore[arg-type]
        self._lp_want[slot] = int(state["lp_want"])  # type: ignore[arg-type]
        self._lp_records[slot] = list(state["lp_records"])  # type: ignore[arg-type]
        self._prompt_lp[slot] = list(state["prompt_lp"])  # type: ignore[arg-type]
        self.min_toks[slot] = state["min_toks"]
        self.gstate[slot] = state["gstate"]
        self._finished.pop(slot, None)
        self._finish_reason.pop(slot, None)
        # penalty histograms rebuild exactly: every device increment
        # was +1.0 on f32 counts, so host bincounts reproduce them
        if state["presence_penalty"] or state["frequency_penalty"]:
            cnt = np.bincount(
                np.asarray(state["outputs"], np.int64),
                minlength=self.model.vocab).astype(np.float32)
            self._counts = _set_count_row(
                self._counts, jnp.int32(slot), jnp.asarray(cnt))
        rec = state["record"]
        if state["repetition_penalty"] != 1.0:
            hist = list(state["outputs"])  # type: ignore[arg-type]
            if rec is not None:
                hist = np.asarray(rec[0], np.int64).tolist() + hist
            sn = np.bincount(
                np.asarray(hist, np.int64),
                minlength=self.model.vocab).astype(np.float32)
            self._seen = _set_count_row(
                self._seen, jnp.int32(slot), jnp.asarray(sn))
        if state["bias"] is not None:
            self._bias = _set_count_row(
                self._bias, jnp.int32(slot),
                jnp.asarray(state["bias"]))
            self._bias_on[slot] = True
        elif self._bias_on[slot]:
            self._bias = _zero_count_row(self._bias, slot)
            self._bias_on[slot] = False
        if state["min_toks"]:
            mask_np = np.zeros(self.model.vocab, np.float32)
            if self.eos_id is not None:
                mask_np[self.eos_id] = -1e6
            for t in state["stops"]:  # type: ignore[union-attr]
                mask_np[int(t)] = -1e6
            self._min_mask = _set_count_row(
                self._min_mask, jnp.int32(slot), jnp.asarray(mask_np))
        self.active[slot] = True
        self._knob_cache = None
        self._fused_cache = None  # restored stops/ignore_eos rows
        if self._inflight_scan is not None:
            self._inflight_scan.skip.add(slot)
        return slot

    # -- session tiering (device tier of the three-tier KV store) ----------

    def park_session(self, slot: int, session_id: str,
                     kept: int) -> int:
        """Park a retired request's slot as the DEVICE tier of its
        conversation: pages stay mapped, the resident-prompt record is
        rewritten to cover the whole conversation (prompt + the *kept*
        output tokens), and the slot turns RESERVED — free_slots()
        skips it and :meth:`_reclaim_parked` cannot take its pages, so
        the only exits are the owning session's next turn (admission
        with the same ``session``) or an explicit
        :meth:`demote_session` / :meth:`discard_session`.

        Rows are reusable up to ``canon`` = rows actually written
        (decode writes a token's K/V when it is FED, one step after
        sampling, so the last kept token's row may be unwritten) and,
        under a speculative proposer, strictly below the clamped
        verify band — the same invariant admit() enforces for
        prompts.  Returns canon."""
        if not self._paged:
            raise RuntimeError("session parking needs kv_paging=True")
        assert self._pool is not None
        rec = self._slot_prompts[slot]
        if rec is None:
            raise ValueError(f"slot {slot} has no resident record")
        if not session_id:
            raise ValueError("empty session_id")
        prompt_np = np.asarray(rec[0], np.int32)
        outs = np.asarray(self.outputs[slot][:kept], np.int32)
        tokens = (np.concatenate([prompt_np, outs])
                  if outs.size else prompt_np)
        canon = min(int(self.lens[slot]), int(tokens.shape[0]))
        if self._draft_model is not None or self._ngram:
            # parked rows must sit strictly below the clamped verify
            # write band [max_len-gamma-1, max_len-1] (see begin_admit)
            canon = min(canon, self.model.max_len - self.gamma - 1)
        canon = max(canon, 0)
        self.active[slot] = False
        self._finished.pop(slot, None)
        self._finish_reason.pop(slot, None)
        self.lens[slot] = 0
        self._slot_prompts[slot] = (tokens, int(rec[1]), canon,
                                    None, None, session_id)
        self._reserved[slot] = True
        self._reset_slot_params(slot)
        if self._inflight_scan is not None:
            self._inflight_scan.skip.add(slot)
        return canon

    def demote_session(self, slot: int) -> Dict[str, object]:
        """Checkpoint a session-PARKED slot (see :meth:`park_session`)
        to host and free its pages + slot — the device → host tier
        transition.  Storage-exact like :meth:`preempt` (int8 pools
        round-trip raw bytes + scales) and codec-clean: the returned
        state is exactly what :meth:`resume_session` — or the migrate
        codec, for the disk tier and cross-replica moves — re-parks
        from."""
        if not self._paged:
            raise RuntimeError("session tiering needs kv_paging=True")
        assert self._pool is not None
        rec = self._slot_prompts[slot]
        if not self._reserved[slot] or rec is None or len(rec) < 6:
            raise ValueError(f"slot {slot} holds no parked session")
        row = jnp.asarray(self._pool.tables[slot])
        raw = jax.device_get(_paged_gather_raw(self.cache, row))
        state: Dict[str, object] = {
            "v": 1,
            "kind": "session",
            "session_id": rec[5],
            "tokens": np.asarray(rec[0], np.int32),
            "canon": int(rec[2]),
            "adapter": int(rec[1]),
            "kv": raw,
        }
        self._pool.clear_slot(slot)
        self._slot_prompts[slot] = None
        self._reserved[slot] = False
        self.lens[slot] = 0
        if self._inflight_scan is not None:
            self._inflight_scan.skip.add(slot)
        return state

    def resume_session(self, state: Dict[str, object]) -> int:
        """Re-park a :meth:`demote_session` checkpoint into a free
        slot: pages re-allocate (reclaiming anonymous parked donors
        under pressure, never preempting), the raw KV scatters back,
        and the slot comes back RESERVED + inactive — exactly the
        state :meth:`park_session` leaves, so the owning session's
        next request takes the same donor match whichever tier the
        record returned from.  Raises RuntimeError (no free slot),
        PagePoolExhausted, or ValueError (malformed state)."""
        if not self._paged:
            raise RuntimeError("session tiering needs kv_paging=True")
        pool = self._pool
        assert pool is not None
        sid = state.get("session_id")
        if not isinstance(sid, str) or not sid:
            raise ValueError("session state carries no session_id")
        if state.get("kind") != "session":
            raise ValueError(
                f"not a session checkpoint: kind={state.get('kind')!r}")
        tokens = np.asarray(state["tokens"], np.int32).reshape(-1)
        canon = int(state["canon"])  # type: ignore[arg-type]
        if not 0 <= canon <= min(int(tokens.shape[0]),
                                 self.model.max_len):
            raise ValueError(f"bad session canon {canon}")
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slots")
        slot = free[0]
        if self._slot_prompts[slot] is not None:
            self._drop_donor(slot)
        pool.clear_slot(slot)
        n_pages = pool.pages_needed(canon)
        got: List[int] = []
        try:
            for _ in range(n_pages):
                while True:
                    try:
                        got.append(pool.alloc())
                        break
                    except PagePoolExhausted:
                        if not self._reclaim_parked():
                            raise
        except PagePoolExhausted:
            for p in got:
                pool.give_back(p)
            raise
        targets = np.full(pool.n_tables, pool.scratch, np.int32)
        for idx, p in enumerate(got):
            pool.map(slot, idx, p)
            targets[idx] = p
        self.cache = _paged_restore_raw(
            self.cache, state["kv"], jnp.asarray(targets),
            jnp.int32(slot), jnp.int32(canon))
        self.lens[slot] = 0
        self._slot_prompts[slot] = (tokens, int(state["adapter"]),  # type: ignore[arg-type]
                                    canon, None, None, sid)
        self._reserved[slot] = True
        self._finished.pop(slot, None)
        self._finish_reason.pop(slot, None)
        self._reset_slot_params(slot)
        if self._inflight_scan is not None:
            self._inflight_scan.skip.add(slot)
        return slot

    def discard_session(self, slot: int) -> None:
        """Drop a parked session outright (tier eviction, or its
        record was superseded by a newer turn): pages freed, record
        gone, slot unreserved."""
        assert self._pool is not None
        rec = self._slot_prompts[slot]
        if not self._reserved[slot] or rec is None or len(rec) < 6:
            raise ValueError(f"slot {slot} holds no parked session")
        self._pool.clear_slot(slot)
        self._slot_prompts[slot] = None
        self._reserved[slot] = False
        self.lens[slot] = 0

    def session_slots(self) -> Dict[str, int]:
        """Map of session_id -> slot for every device-parked
        session."""
        out: Dict[str, int] = {}
        for s, rec in enumerate(self._slot_prompts):
            if rec is not None and len(rec) > 5 and self._reserved[s]:
                out[rec[5]] = s
        return out

    # -- admission ---------------------------------------------------------

    @property
    def scan_inflight(self) -> bool:
        """A dispatched-but-unharvested window is open (the scheduler's
        mid-window-admission stamp)."""
        return self._inflight_scan is not None

    def free_slots(self) -> List[int]:
        return [s for s in range(self.n_slots)
                if not self.active[s] and not self._reserved[s]]

    def _prefill_job(self, mini, toks, start: int,
                     adapter: int = -1, plp_k: int = 0,
                     plp_out: Optional[list] = None) -> "_PrefillJob":
        """Build the chunk-at-a-time prefill driver for *toks* [1, n]
        into the B=1 *mini* cache at depth *start*.  ONE implementation
        (:class:`_PrefillJob`) serves the one-shot admit, the iteration
        scheduler's serial chunk interleave, AND the ragged packed path
        (:meth:`admit_step_packed`), so the three cannot drift — chunk
        decomposition, padding, plp rows, and the final cache_lens fix
        are byte-for-byte the same ops in the same order."""
        return _PrefillJob(self, mini, np.asarray(toks, np.int32),
                           start, adapter, plp_k, plp_out)

    def _extend_prompt(self, mini, toks, start: int,
                       adapter: int = -1, plp_k: int = 0,
                       plp_out: Optional[list] = None):
        """Push *toks* [1, n] into the B=1 *mini* cache starting at
        depth *start*; returns (mini, last real token's logits row).
        With *plp_k*, per-chunk prompt-logprob stats (row j scores the
        NEXT prompt token) are appended to *plp_out* as device arrays
        — same compiled shapes as the extends themselves."""
        job = self._prefill_job(mini, toks, start, adapter=adapter,
                                plp_k=plp_k, plp_out=plp_out)
        while job.remaining:
            job.step()
        return job.mini, job.last

    def _draft_prefill(self, prompt):
        """Cold-prefill the draft with the FULL prompt on the engine's
        chunk grid (no prefix reuse — the target's K/V cannot seed a
        different model's cache).  Returns a B=1 draft mini holding
        t_p rows."""
        n = int(prompt.shape[1])
        mini = self._place_cache(init_cache(self._draft_model, 1))
        c = self.chunk
        if c is None:
            pos = jnp.arange(n, dtype=jnp.int32)[None, :]
            _, mini = extend_step(
                self._draft_model, self._draft_params, mini, prompt, pos)
            return mini
        padded = ((n + c - 1) // c) * c
        toks = jnp.concatenate(
            [prompt, jnp.zeros((1, padded - n), jnp.int32)], axis=1)
        for i in range(padded // c):
            pos = (jnp.arange(c, dtype=jnp.int32) + i * c)[None, :]
            _, mini = extend_step(
                self._draft_model, self._draft_params, mini,
                toks[:, i * c:(i + 1) * c], pos)
        return _set_len(mini, jnp.int32(0), jnp.int32(n))

    def _adapter_vec(self, adapter: int):
        """[1]-shaped adapter-id operand, or None for non-LoRA models
        (keeps their compiled extends identical to before)."""
        if self.model.n_adapters == 0:
            return None
        return jnp.asarray([adapter], jnp.int32)

    def _check_adapter(self, adapter) -> int:
        if adapter is None:
            return -1
        if self.model.n_adapters == 0:
            raise ValueError(
                "model was built without LoRA adapters (n_adapters=0)")
        if not 0 <= adapter < self.model.n_adapters:
            raise ValueError(
                f"adapter {adapter} outside [0, "
                f"{self.model.n_adapters})")
        return adapter

    def _auto_match(self, pnp: np.ndarray, t_p: int, aid: int,
                    session: Optional[str] = None):
        """Find the best automatic prefix donor for *prompt*: the
        registry entry or resident slot prompt sharing the longest
        common prefix, measured in whole chunks (reuse stays on the
        chunk grid, so reused K/V is bit-identical to what cold
        chunked admission would compute).  The match is capped at
        t_p - 1 — the last prompt token always recomputes so admission
        has its logits row (same rule as vLLM's APC).  Returns
        (kind, ref, m) or None; donors are adapter-bound (the adapter
        shapes the K/V).

        EXACT matches skip even that last token: a donor whose prompt
        IS the new prompt (full length on the chunk grid) carries the
        admission-time logits row of its final position, so the
        admission is pure data movement — splice + the stored row —
        with zero extends (kinds "reg_full"/"slot_full", m = t_p).
        The row is the same device value a cold admission computes, so
        tokens stay bit-identical (the house invariant).

        SESSION records (a 6-tuple whose rec[5] names the owning
        conversation, see :meth:`park_session`) are conversation-
        private: their rows past the original prompt were written by
        DECODE steps, not chunk-grid prefill, so they are bit-exact
        continuations of that one conversation but not of a cold
        chunked admission.  Foreign traffic must never match them —
        and the owning session's request matches its own record FIRST
        (before any anonymous donor), so the continuation takes the
        same donor whichever tier the record came back from."""
        if not self.auto_prefix:
            return None
        c = self.chunk
        best = None
        best_m = 0
        for h, (ptoks, _pc, _pl, paid) in self._prefixes.items():
            if paid != aid:
                continue
            lcp = _lcp(pnp, ptoks)
            if lcp == t_p == len(ptoks):
                return ("reg_full", h, t_p)
            m = (min(lcp, t_p - 1) // c) * c
            if m > best_m:
                best_m, best = m, ("reg", h, m)
        for s, rec in enumerate(self._slot_prompts):
            if rec is None:
                continue
            rec_sess = rec[5] if len(rec) > 5 else None
            if rec_sess is not None and rec_sess != session:
                continue  # another conversation's decode rows
            stoks, said, canon = rec[0], rec[1], rec[2]
            if said != aid:
                continue
            lcp = _lcp(pnp, stoks)
            if rec_sess is not None:
                # the conversation's own parked KV wins outright when
                # it is usable: tiers all converge to this one match
                m = (min(lcp, canon, t_p - 1) // c) * c
                if m >= max(1, self.auto_prefix_min):
                    return ("slot", s, m)
                continue
            if (lcp == t_p == len(stoks) and canon == t_p
                    and rec[3] is not None):
                return ("slot_full", s, t_p)
            m = (min(lcp, canon, t_p - 1) // c) * c
            if m > best_m:
                best_m, best = m, ("slot", s, m)
        if best_m < max(1, self.auto_prefix_min):
            return None
        return best

    def _touch_prefix(self, handle: int) -> None:
        """LRU stamp: a registry entry was used (registered, matched,
        or explicitly admitted against)."""
        self._use_seq += 1
        self._prefix_touch[handle] = self._use_seq

    def register_prefix(self, tokens, adapter: Optional[int] = None) -> int:
        """Prefill a shared prompt prefix (e.g. a system prompt) ONCE
        and reuse it across admits: ``admit(prompt, prefix=handle)``
        skips recomputing the first ``len(tokens)`` positions.  Returns
        an opaque handle.  A prefix is bound to its ``adapter`` (the
        adapter shapes the prefix K/V!); admits must request the same
        one.

        The registry is BOUNDED (``prefix_registry_max``, default a
        generous 256): each handle pins a full [1, T_max, Hkv, Dh]
        per-layer cache, so a long-lived server registering freely
        would grow host/device bookkeeping without limit.  Past the
        cap, the least-recently-used entry is evicted (counted in
        ``prefix_evictions``) — exactly what an explicit
        :meth:`release_prefix` would have done."""
        toks = jnp.asarray(tokens, jnp.int32).reshape(1, -1)
        if int(toks.shape[1]) < 1:
            raise ValueError("empty prefix")
        aid = self._check_adapter(adapter)
        while len(self._prefixes) >= self.prefix_registry_max:
            lru = min(self._prefixes,
                      key=lambda h: self._prefix_touch.get(h, 0))
            self._prefixes.pop(lru, None)
            self._prefix_touch.pop(lru, None)
            self._prefix_evictions += 1
        mini = self._place_cache(init_cache(self.model, 1))
        mini, last = self._extend_prompt(mini, toks, start=0, adapter=aid)
        handle = self._next_prefix
        self._next_prefix += 1
        self._prefixes[handle] = (
            np.asarray(toks[0], np.int32), mini, last, aid)
        self._touch_prefix(handle)
        return handle

    def release_prefix(self, handle: int) -> None:
        """Drop a registered prefix.  Each handle retains a full
        [1, T_max, Hkv, Dh] per-layer cache (sized for max_len, not the
        prefix — splice and extend need full rows), so long-running
        engines should release prefixes they no longer admit against
        (the ``prefix_registry_max`` LRU cap is the backstop)."""
        self._prefixes.pop(handle, None)
        self._prefix_touch.pop(handle, None)

    def _slot_src(self, ref: int):
        """Donor slot rows as a B=1 mini cache: a contiguous copy-out,
        or a pool gather by the donor's block table in paged mode."""
        if self._paged:
            assert self._pool is not None
            return self._place_cache(_paged_gather_mini(
                self.cache, jnp.asarray(self._pool.tables[ref]),
                self.model.dtype))
        return self._place_cache(_slot_to_mini(self.cache,
                                               jnp.int32(ref)))

    def _paged_land(self, st: AdmitState, mini) -> None:
        """Finish-side block-table build for a paged admission: clear
        the slot's stale mappings, install the begin-time prefix
        shares, allocate owned pages for the prefilled suffix, and
        splice the mini into THOSE pages only (shared entries target
        the scratch page — a shared page is never written while
        shared).  Pure-share landings (exact repeats) skip the splice:
        one cache_lens fix and the tokens flow."""
        pool = self._pool
        assert pool is not None
        slot = st.slot
        ps = pool.page_size
        # incref-at-begin makes this safe even when the donor IS this
        # slot: clear unrefs the old mappings, the share refs keep the
        # pages alive, map_shared re-installs them
        pool.clear_slot(slot)
        pool.map_shared(slot, st.share_pages)
        shared_n = len(st.share_pages)
        st.share_pages = []  # consumed by the table
        end_page = (st.prefill_end + ps - 1) // ps
        try:
            for idx in range(shared_n, end_page):
                pool.map(slot, idx, self._alloc_page())
        except PagePoolExhausted:
            # roll the landing back; the slot reservation stands and
            # the caller aborts or retries (rare: the begin-time gate
            # budgeted these pages — only a mid-flight decode burst
            # can have taken them).  The previous occupant's donor
            # record lost its pages with the clear, so it dies too.
            pool.clear_slot(slot)
            self._slot_prompts[slot] = None
            raise
        if mini is None:
            self.cache = _set_len(self.cache, jnp.int32(slot),
                                  jnp.int32(st.t_p))
        else:
            targets = np.full(pool.n_tables, pool.scratch, np.int32)
            row = pool.tables[slot]
            targets[shared_n:end_page] = row[shared_n:end_page]
            self.cache = _paged_splice(
                self.cache, mini, jnp.asarray(targets),
                jnp.int32(slot), jnp.int32(st.t_p))

    def admit(self, prompt, prefix: Optional[int] = None,
              temperature: float = 0.0,
              top_k: Optional[int] = None,
              top_p: float = 1.0,
              min_p: float = 0.0,
              presence_penalty: float = 0.0,
              frequency_penalty: float = 0.0,
              repetition_penalty: float = 1.0,
              seed: Optional[int] = None,
              seed_stream: int = 0,
              adapter: Optional[int] = None,
              stop: Optional[List[int]] = None,
              ignore_eos: bool = False,
              logprobs: Optional[int] = None,
              prompt_logprobs: Optional[int] = None,
              logit_bias: Optional[Dict[int, float]] = None,
              min_tokens: int = 0,
              grammar: Union[bool, int] = False,
              session: Optional[str] = None) -> int:
        """Prefill *prompt* into a free slot; returns the slot id.
        Raises RuntimeError when the engine is full (callers queue).
        With ``prefix`` (a :meth:`register_prefix` handle), the prompt
        must start with the registered tokens and only the suffix is
        prefilled — the prefix K/V is copied from the registry.
        Without a handle, automatic prefix caching (on by default for
        chunked engines) matches the prompt against resident slot
        prompts and the registry at chunk granularity and prefills
        only the unmatched tail — reused rows lie on the same chunk
        grid cold admission would compute, so tokens stay
        bit-identical.  ``temperature``/``top_k`` select this
        request's sampling (0 / None = greedy) and ``stop`` lists
        per-request stop-token ids — per-slot data, never a
        recompile.

        One-shot driver of the split admission API (begin_admit →
        admit_step* → finish_admit) — the iteration scheduler runs the
        same pieces spread across decode slices, so both paths are the
        same ops in the same order and emit bit-identical tokens."""
        st = self.begin_admit(
            prompt, prefix=prefix, temperature=temperature,
            top_k=top_k, top_p=top_p, min_p=min_p,
            presence_penalty=presence_penalty,
            frequency_penalty=frequency_penalty,
            repetition_penalty=repetition_penalty,
            seed=seed, seed_stream=seed_stream, adapter=adapter,
            stop=stop, ignore_eos=ignore_eos, logprobs=logprobs,
            prompt_logprobs=prompt_logprobs, logit_bias=logit_bias,
            min_tokens=min_tokens, grammar=grammar, session=session)
        try:
            while self.admit_step(st):
                pass
            return self.finish_admit(st)
        except BaseException:
            if not st.spliced:
                self.abort_admit(st)
            raise

    def begin_admit(self, prompt, prefix: Optional[int] = None,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    top_p: float = 1.0,
                    min_p: float = 0.0,
                    presence_penalty: float = 0.0,
                    frequency_penalty: float = 0.0,
                    repetition_penalty: float = 1.0,
                    seed: Optional[int] = None,
                    seed_stream: int = 0,
                    adapter: Optional[int] = None,
                    stop: Optional[List[int]] = None,
                    ignore_eos: bool = False,
                    logprobs: Optional[int] = None,
                    prompt_logprobs: Optional[int] = None,
                    logit_bias: Optional[Dict[int, float]] = None,
                    min_tokens: int = 0,
                    grammar: Union[bool, int] = False,
                    session: Optional[str] = None) -> AdmitState:
        """Validate a request, reserve a free slot, and set up its
        chunked prefill WITHOUT running it: the returned
        :class:`AdmitState` is advanced one chunk per
        :meth:`admit_step` and lands via :meth:`finish_admit` (or is
        abandoned via :meth:`abort_admit`).  Every admit() validation
        error raises HERE, before any engine state is touched, so a
        rejected request can never strand a half-reserved slot."""
        # ONE host-side copy serves validation, auto-matching, and the
        # resident-prompt record; the device transfer happens once here
        prompt_np = np.asarray(prompt, np.int32).reshape(1, -1)
        prompt = jnp.asarray(prompt_np)
        t_p = int(prompt.shape[1])
        if t_p < 1:
            raise ValueError("empty prompt")
        if int(prompt_np.min()) < 0 or int(prompt_np.max()) >= \
                self.model.vocab:
            # validate BEFORE any state mutation: a bad id must reject
            # cleanly, not corrupt a half-admitted slot (and the
            # repetition-penalty histogram would otherwise bincount to
            # the wrong width)
            raise ValueError(
                f"prompt token outside [0, vocab={self.model.vocab})")
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        validate_top_k(self.model, top_k)
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p {top_p} outside (0, 1]")
        if not 0.0 <= min_p <= 1.0:
            raise ValueError(f"min_p {min_p} outside [0, 1]")
        for pname, pval in (("presence_penalty", presence_penalty),
                            ("frequency_penalty", frequency_penalty)):
            if not -2.0 <= pval <= 2.0:
                raise ValueError(
                    f"{pname} {pval} outside [-2, 2]")
        if not repetition_penalty > 0:
            raise ValueError(
                f"repetition_penalty {repetition_penalty} must be > 0")
        aid = self._check_adapter(adapter)
        stops = frozenset(int(t) for t in (stop or ()))
        for t in stops:
            if not 0 <= t < self.model.vocab:
                raise ValueError(
                    f"stop token {t} outside [0, vocab="
                    f"{self.model.vocab})")
        lp_n = int(logprobs or 0)
        plp_n = int(prompt_logprobs or 0)
        for nm, v in (("logprobs", lp_n), ("prompt_logprobs", plp_n)):
            if v < 0:
                raise ValueError(f"{nm} must be >= 0")
            if v > self.logprobs_k:
                raise ValueError(
                    f"{nm}={v} exceeds the engine's logprobs_k="
                    f"{self.logprobs_k} (set at construction — the "
                    "engine-wide k keeps the decode step "
                    "compile-stable)")
        if plp_n and prefix is not None:
            raise ValueError(
                "prompt_logprobs needs the full prompt prefilled — "
                "incompatible with a prefix handle")
        budget = self.max_new_tokens or 1
        if t_p + budget > self.model.max_len:
            raise ValueError(
                f"prompt {t_p} + budget {budget} exceeds "
                f"max_len {self.model.max_len}")
        # t_p <= max_len - 1 is also the prefix-cache donor invariant
        # (see release()): a parked slot's masked decode writes clamp to
        # row max_len - 1, which this bound keeps out of the prompt
        # rows, so released-slot donor records stay valid K/V
        assert t_p <= self.model.max_len - 1
        if (self._draft_model is not None or self._ngram) \
                and self.auto_prefix:
            # with a speculative proposer the donor invariant is
            # STRONGER: spec_round's verify extend writes T = gamma+1
            # rows for EVERY slot, and a parked slot's clamped write
            # band is [max_len-gamma-1, max_len-1] — prompt K/V must
            # sit strictly below it or later rounds silently corrupt
            # the slot's APC donor rows.  Gated on auto_prefix: with
            # donor matching off, parked rows are never read back and
            # the clamped writes are harmless (spec_round's headroom
            # fallback already protects live slots)
            spec_limit = self.model.max_len - self.gamma - 1
            if t_p > spec_limit:
                raise ValueError(
                    f"prompt {t_p} exceeds the speculative donor bound "
                    f"{spec_limit} (max_len - gamma - 1): parked-slot "
                    "prompt K/V must stay below the clamped verify "
                    "band; shorten the prompt, raise max_len, or "
                    "lower gamma")
        # grammar opt-in: True = grammar 0 (the ctor grammar), an int
        # selects a register_grammar() id; gstart -1 = unconstrained
        if grammar is False or grammar is None:
            gstart = -1
        else:
            if not self._goffsets:
                raise ValueError(
                    "engine has no grammar registered "
                    "(ServingEngine(..., grammar=TokenDfa) or "
                    "register_grammar())")
            gid = 0 if grammar is True else int(grammar)
            if not 0 <= gid < len(self._goffsets):
                raise ValueError(
                    f"unknown grammar id {gid} (registered: "
                    f"{len(self._goffsets)})")
            gstart = self._goffsets[gid]
        if min_tokens < 0:
            raise ValueError("min_tokens must be >= 0")
        if (min_tokens and self.max_new_tokens is not None
                and min_tokens > self.max_new_tokens):
            raise ValueError(
                f"min_tokens {min_tokens} exceeds the engine budget "
                f"{self.max_new_tokens}")
        if logit_bias is not None:
            if not isinstance(logit_bias, dict) or not logit_bias:
                raise ValueError(
                    "logit_bias must be a non-empty {token: bias} dict")
            for bk, bv in logit_bias.items():
                if isinstance(bk, bool) or not isinstance(
                        bk, (int, np.integer)):
                    raise ValueError(
                        "logit_bias keys must be token ids")
                if not 0 <= int(bk) < self.model.vocab:
                    raise ValueError(
                        f"logit_bias token {bk} outside "
                        f"[0, vocab={self.model.vocab})")
                if not np.isfinite(float(bv)):
                    raise ValueError(
                        "logit_bias values must be finite")
                if not -100.0 <= float(bv) <= 100.0:
                    # OpenAI clamps to [-100, 100]; beyond that a bias
                    # could overpower the -1e6/-1e9 additive masks that
                    # implement min_tokens floors and grammar
                    # constraints
                    raise ValueError(
                        f"logit_bias value {float(bv)} outside "
                        "[-100, 100]")
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slots")
        slot = free[0]

        # validate EVERYTHING before touching any slot bookkeeping — a
        # rejected admit must leave the engine state untouched
        auto_src = None
        L = 0
        if prefix is not None:
            if prefix not in self._prefixes:
                raise ValueError(f"unknown prefix handle {prefix}")
            ptoks, pcache, plast, paid = self._prefixes[prefix]
            L = len(ptoks)
            if t_p < L or not np.array_equal(prompt_np[0, :L], ptoks):
                raise ValueError(
                    "prompt does not start with the registered prefix")
            if paid != aid:
                raise ValueError(
                    f"prefix was registered with adapter {paid}, "
                    f"request uses {aid} — the adapter shapes the "
                    "prefix K/V, register one per adapter")
            start, n = L, t_p - L
        else:
            # prompt_logprobs needs every position's logits, so it
            # forces a full (cold) prefill — no automatic prefix reuse
            auto_src = (None if plp_n
                        else self._auto_match(prompt_np[0], t_p, aid,
                                              session or None))
            start = auto_src[2] if auto_src is not None else 0
            n = t_p - start
        if self.chunk is not None and n > 0:
            padded = ((n + self.chunk - 1) // self.chunk) * self.chunk
            if start + padded > self.model.max_len:
                raise ValueError(
                    f"padded prompt {start + padded} exceeds max_len "
                    f"{self.model.max_len} (shrink chunk or prompt)")
        if (auto_src is not None and auto_src[0] == "slot_full"
                and not self.active[auto_src[1]]
                and not self._reserved[auto_src[1]]):
            # prefix-affinity placement: an exact repeat goes back
            # into its donor's FREE slot, where the "copy" is the
            # identity — admission reduces to one cache_lens fix
            slot = auto_src[1]

        st = AdmitState()
        st.slot = slot
        st.prompt_np = prompt_np
        st.prompt = prompt
        st.t_p = t_p
        st.aid = aid
        st.stops = stops
        st.temperature = temperature
        st.top_k = top_k
        st.top_p = top_p
        st.min_p = min_p
        st.presence_penalty = presence_penalty
        st.frequency_penalty = frequency_penalty
        st.repetition_penalty = repetition_penalty
        st.seed = seed
        st.seed_stream = seed_stream
        st.ignore_eos = ignore_eos
        st.min_tokens = min_tokens
        st.lp_n = lp_n
        st.plp_n = plp_n
        st.logit_bias = logit_bias
        st.gstart = gstart
        st.auto_src = auto_src
        # explicit-prefix admits with an unaligned prefix leave the
        # suffix rows off the chunk grid — only the prefix part is
        # reusable by future automatic matches
        if (self.chunk is not None and prefix is not None
                and L % self.chunk):
            st.canon = L
        else:
            st.canon = t_p
        if n <= 0:
            st.chunks_total = 0
        elif self.chunk is None:
            st.chunks_total = 1
        else:
            st.chunks_total = (n + self.chunk - 1) // self.chunk

        if self._paged:
            # page-budget gate: rows [start_shared, prefill_end) need
            # owned pages at finish.  Reclaim parked donor pages until
            # the budget fits or raise HERE (nothing mutated yet) —
            # PagePoolExhausted at begin is the server's cue to apply
            # QoS policy (preempt a lower-priority slot or re-queue),
            # where the contiguous engine could only ever say
            # "no free slots".
            assert self._pool is not None
            ps = self._pool.page_size
            c = self.chunk
            st.prefill_end = (start + ((n + c - 1) // c) * c
                              if n > 0 else t_p)
            if auto_src is not None and auto_src[0] == "slot_full" \
                    and self._draft_model is None:
                # inplace or page-share landing: nothing allocated
                shared_est = (t_p + ps - 1) // ps
            elif auto_src is not None and auto_src[0] == "slot":
                shared_est = auto_src[2] // ps
            else:
                shared_est = 0
            need = (st.prefill_end + ps - 1) // ps - shared_est
            if need > self._pool.n_pages:
                raise ValueError(
                    f"prompt needs {need} KV pages, pool holds "
                    f"{self._pool.n_pages}")
            while (self._pool.free_pages() < need
                   and self._reclaim_parked()):
                pass
            if self._pool.free_pages() < need:
                raise PagePoolExhausted(
                    f"admission needs {need} KV pages, "
                    f"{self._pool.free_pages()} free")

        if prefix is not None:
            self._touch_prefix(prefix)
            if n > 0:
                # copy before extending: extend_step DONATES its cache,
                # and the registry entry must survive for the next admit
                mini = jax.tree_util.tree_map(jnp.copy, pcache)
                st.gen = self._prefill_job(
                    mini, prompt_np[:, L:], start=L, adapter=aid)
            else:
                # exact-prefix prompt: no extend runs, and _splice_slot
                # does not donate its mini argument, so the registry
                # cache splices directly — no copy
                st.result = (pcache, plast)
        elif auto_src is not None:
            kind, ref, m = auto_src
            if kind in ("reg", "reg_full"):
                self._touch_prefix(ref)
            if kind == "reg_full":
                # exact registry prompt: zero extends, no copy
                # (_splice_slot does not donate its mini) — identical
                # to an explicit exact-prefix handle admit
                _, pc_full, pl_full, _ = self._prefixes[ref]
                st.result = (pc_full, pl_full)
            elif kind == "slot_full":
                # exact resident prompt: reuse the donor rows and the
                # stored final-position logits row — admission becomes
                # pure data movement (the vLLM full-prompt cache hit)
                rec_full = self._slot_prompts[ref]
                if ref == slot and self._draft_model is None:
                    # prefix-affinity placement put us IN the donor
                    # slot: no copy at all, finish just fixes the
                    # slot's cache_lens back to t_p
                    st.inplace = True
                    st.result = (None, rec_full[3])
                elif self._paged and self._draft_model is None:
                    # paged exact repeat into a DIFFERENT slot: no
                    # copy either — the slot maps the donor's pages by
                    # reference (refcounted; the first append past the
                    # shared rows pays one CoW page copy instead of
                    # the contiguous path's full-row splice)
                    assert self._pool is not None
                    st.share_pages = self._pool.share(
                        ref, (t_p + self._pool.page_size - 1)
                        // self._pool.page_size)
                    st.result = (None, rec_full[3])
                else:
                    src = self._slot_src(ref)
                    st.result = (
                        _set_len(src, jnp.int32(0), jnp.int32(t_p)),
                        rec_full[3])
                if len(rec_full) > 4:
                    st.first_cached = rec_full[4]
            else:
                if kind == "reg":
                    # registry entries must survive — copy before
                    # donating
                    src = jax.tree_util.tree_map(
                        jnp.copy, self._prefixes[ref][1])
                else:
                    src = self._slot_src(ref)
                    if self._paged:
                        # the matched prefix pages map by reference;
                        # only the suffix (and the boundary page, if
                        # the grid ever splits one) lands owned.  The
                        # gathered mini still materializes the prefix
                        # rows — the suffix extend attends to them —
                        # but the POOL keeps one copy.
                        assert self._pool is not None
                        st.share_pages = self._pool.share(
                            ref, m // self._pool.page_size)
                # rows beyond m are stale donor data masked out by the
                # cache_lens reset; the suffix extend overwrites
                # [m, ...)
                mini = _set_len(src, jnp.int32(0), jnp.int32(m))
                st.gen = self._prefill_job(
                    mini, prompt_np[:, m:], start=m, adapter=aid)
        else:
            mini = self._place_cache(init_cache(self.model, 1))
            st.gen = self._prefill_job(
                mini, prompt_np, start=0, adapter=aid,
                plp_k=self.logprobs_k if plp_n else 0,
                plp_out=st.plp_dev)
        # reservation is the LAST begin-side mutation: everything above
        # may raise, and a rejected begin must leave the engine exactly
        # as it found it (share_pages refcounts are rolled back by
        # abort_admit, the one begin-side effect with a paired undo)
        self._reserved[slot] = True
        return st

    def admit_step(self, st: AdmitState) -> bool:
        """Dispatch the next prefill chunk of an in-flight admission;
        returns True while chunks remain.  Each call enqueues ONE
        compiled extend (async dispatch — the host returns before the
        device finishes), which is what lets the iteration scheduler
        slide prefill chunks between decode slices."""
        if st.gen is None:
            return False
        job = st.gen
        job.step()
        st.chunks_done += 1
        st.result = (job.mini, job.last)
        if job.remaining == 0:
            st.gen = None
            return False
        return True

    def admit_step_packed(self, states: List[AdmitState],
                          rounds: int = 1) -> None:
        """Advance EACH of *states* by *rounds* prefill chunks through
        batched extends — the ragged packed prefill.  The K B=1
        admission caches stack ONCE into one B=K cache
        (``_pack_minis``), every round runs one ``extend_step`` with
        all K chunks at their own depths (per-row positions, per-row
        cache_lens — exactly the decode cache's per-slot machinery),
        and the result splits back ONCE at the end.  Host dispatches
        per chunk-round drop from K to ~1, the pack/unpack copies
        amortize over the whole session, and on parallel hardware the
        K extends share one kernel's MXU pass.

        Byte-identity: each packed row's operands and bookkeeping come
        from the same :class:`_PrefillJob` methods the serial path
        uses, and a batched extend computes rows independently — the
        packed equivalence suite pins streams bit-for-bit against the
        serial path.  Callers guarantee every state is mid-prefill
        (``st.gen`` set) and packable, len(states) >= 2, and *rounds*
        <= every state's remaining chunks; pack sizes form a small
        fixed compile set (see ``warm_packed``)."""
        jobs = []
        for st in states:
            job = st.gen
            if job is None or not job.packable or not job.remaining:
                raise ValueError(
                    "admit_step_packed needs in-flight packable "
                    "admissions")
            jobs.append(job)
        k = len(jobs)
        if k < 2:
            raise ValueError("a pack needs >= 2 admissions")
        if rounds < 1 or any(j.remaining < rounds for j in jobs):
            raise ValueError(
                "rounds must be >= 1 and <= every job's remaining "
                "chunks")
        aids = (None if self.model.n_adapters == 0 else
                jnp.asarray([j.aid for j in jobs], jnp.int32))
        for job in jobs:
            job.charge()
            if not job.packed_used:
                job.packed_used = True
                self._packed_requests += 1
        packed = _pack_minis(tuple(j.mini for j in jobs))
        for _ in range(rounds):
            toks = np.concatenate([j.chunk_np() for j in jobs],
                                  axis=0)
            pos = np.concatenate([j.pos_np() for j in jobs], axis=0)
            for job in jobs:
                self._packed_pad_tokens += job.pad_rows()
            logits, packed = extend_step(
                self.model, self.params, packed, jnp.asarray(toks),
                jnp.asarray(pos), aids)
            self._packed_extends += 1
            self._packed_rows += k
            for i, job in enumerate(jobs):
                job.absorb_logits(logits[i])
        minis = _unpack_minis(packed, k)
        for i, (st, job) in enumerate(zip(states, jobs)):
            job.attach_mini(minis[i])
            st.chunks_done += rounds
            st.result = (job.mini, job.last)
            if job.remaining == 0:
                st.gen = None

    def warm_packed(self, sizes) -> None:
        """Pre-compile the packed-prefill shape set: one throwaway
        packed extend per pack size in *sizes* (each [K, chunk] shape
        is its own XLA compile — without this the first packed convoy
        eats the compile mid-traffic).  No engine state is touched;
        unchunked engines have no packed path and return immediately."""
        if self.chunk is None:
            return
        c = self.chunk
        out = None
        for k in sorted(set(int(s) for s in sizes)):
            if k < 2:
                continue
            minis = tuple(self._place_cache(init_cache(self.model, 1))
                          for _ in range(k))
            toks = jnp.zeros((k, c), jnp.int32)
            pos = jnp.broadcast_to(
                jnp.arange(c, dtype=jnp.int32), (k, c))
            aids = (None if self.model.n_adapters == 0 else
                    jnp.zeros((k,), jnp.int32))
            packed = _pack_minis(minis)
            out, packed = extend_step(
                self.model, self.params, packed, toks, pos, aids)
            _unpack_minis(packed, k)
        if out is not None:
            jax.block_until_ready(out)

    def abort_admit(self, st: AdmitState) -> None:
        """Abandon an in-flight admission (client went away before its
        prefill landed): the reserved slot returns to the free pool and
        the mini cache is dropped.  Tokens already prefilled show up in
        ``prefill_tokens`` (they did run); nothing else was touched."""
        if st.spliced:
            raise RuntimeError(
                "admission already finished; release() the slot")
        if st.gen is not None:
            st.gen.close()
            st.gen = None
        st.result = None
        if st.share_pages:
            # roll back the begin-time prefix-share refcounts
            assert self._pool is not None
            self._pool.unshare(st.share_pages)
            st.share_pages = []
        self._reserved[st.slot] = False

    def finish_admit(self, st: AdmitState) -> int:
        """Land a fully-prefilled admission: splice the mini cache into
        the slot, arm the request's knobs, and sample its first token.
        Returns the slot id (the request is live from here)."""
        self._finish_admit_dispatch(st)
        return self._finish_admit_resolve(st)

    def _finish_admit_dispatch(self, st: AdmitState) -> None:
        """Device-dispatch half of finish_admit: splice + knob arming +
        the first-token pick, all enqueued WITHOUT a host-device sync —
        the pick stays on device (``st.pick``) until
        :meth:`_finish_admit_resolve` materializes it.  The scheduler
        runs this between a window's dispatch and harvest (the one
        blocking sync then covers the scan AND the admission)."""
        if not st.ready:
            raise RuntimeError("admission prefill not finished "
                               "(admit_step until it returns False)")
        slot = st.slot
        mini, last = st.result
        # a default-knob admission into a reset slot writes only
        # values the slot already holds (reset_slot_params reset the
        # sampling vectors; the remaining three are checked here), so
        # the device knob mirrors stay valid and the next window skips
        # ~a dozen host->device rebuilds
        knobs_same = (st.seed is None and st.aid == -1
                      and self._clean_greedy_admit(st)
                      and int(self.min_toks[slot]) == 0
                      and int(self.seeds[slot]) == 0
                      and int(self._seed_streams[slot])
                      == int(st.seed_stream))
        # recycling a slot must drop the previous request's finished
        # record, or finished(slot) would report True for the new
        # in-flight request
        self._finished.pop(slot, None)
        self._finish_reason.pop(slot, None)
        self._prompt_lp[slot] = []
        if st.auto_src is not None:
            self._prefix_hits += 1
            self._prefix_reused_tokens += st.auto_src[2]
        if st.inplace:
            # the donor rows already live in this slot: restore the
            # prompt length over the parked-clamp value and the splice
            # is done
            self.cache = _set_len(self.cache, jnp.int32(slot),
                                  jnp.int32(st.t_p))
        elif self._paged:
            self._paged_land(st, mini)
        else:
            self.cache = _splice_slot(self.cache, mini,
                                      jnp.int32(slot))
        if self._draft_model is not None:
            self._draft_cache = _splice_slot(
                self._draft_cache, self._draft_prefill(st.prompt),
                jnp.int32(slot))
        # the final-position logits row rides the record: an exact
        # repeat of this prompt admits with zero extends (see
        # _auto_match's "slot_full"); resolve fills the cached greedy
        # first token when this admission qualifies
        self._slot_prompts[slot] = (st.prompt_np[0], st.aid, st.canon,
                                    last, None)
        self.lens[slot] = st.t_p
        self.active[slot] = True
        self.temps[slot] = st.temperature
        self.topks[slot] = st.top_k or 0
        self.topps[slot] = st.top_p
        self.minps[slot] = st.min_p
        self.pres[slot] = st.presence_penalty
        self.freqs[slot] = st.frequency_penalty
        self.reps[slot] = st.repetition_penalty
        self.adapters[slot] = st.aid
        if (self._stops[slot] != st.stops
                or self._ignore_eos[slot] != bool(st.ignore_eos)):
            # the fused boundary mirrors key on stops/ignore_eos, which
            # a knob-identical admission can still change — they get
            # their own staleness check, independent of knobs_same
            self._fused_cache = None
        self._stops[slot] = st.stops
        self._ignore_eos[slot] = bool(st.ignore_eos)
        if st.logit_bias:
            bias_np = np.zeros(self.model.vocab, np.float32)
            for bk, bv in st.logit_bias.items():
                bias_np[int(bk)] = float(bv)
            row_dev = jnp.asarray(bias_np)  # ONE host-to-device copy
            self._bias = _set_count_row(
                self._bias, jnp.int32(slot), row_dev)
            self._bias_on[slot] = True
            bias_row = row_dev[None, :]
        else:
            if self._bias_on[slot]:
                # stale row from a previous biased occupant: there is
                # no knob masking the add, so it must be zeroed
                self._bias = _zero_count_row(self._bias, slot)
                self._bias_on[slot] = False
            bias_row = None
        self.gstate[slot] = st.gstart
        self.min_toks[slot] = st.min_tokens
        min_row = None
        if st.min_tokens:
            mask_np = np.zeros(self.model.vocab, np.float32)
            if self.eos_id is not None:
                mask_np[self.eos_id] = -1e6
            for t in st.stops:
                mask_np[t] = -1e6
            row_dev = jnp.asarray(mask_np)
            self._min_mask = _set_count_row(
                self._min_mask, jnp.int32(slot), row_dev)
            min_row = row_dev[None, :]  # first pick has 0 emitted
        self.seeds[slot] = np.uint32((st.seed or 0) & 0xFFFFFFFF)
        self._seed_streams[slot] = int(st.seed_stream)
        self._seed_on[slot] = 0 if st.seed is None else 1
        if not knobs_same:
            self._knob_cache = None  # device mirrors are stale now
        self._slot_draws[slot] = 0
        self._lp_want[slot] = st.lp_n
        self._lp_records[slot] = []
        # first token: the OUTPUT histogram is empty by definition
        # (presence/frequency no-op), but the repetition penalty scopes
        # over the prompt — host bincount, no per-length compiles
        draws_before = self._draws
        rep_on = st.repetition_penalty != 1.0
        if rep_on:
            seen_row = jnp.asarray(np.bincount(
                st.prompt_np[0], minlength=self.model.vocab
            ).astype(np.float32))[None, :]
        else:
            seen_row = self._zero_vocab_row
        if (st.first_cached is not None
                and self._clean_greedy_admit(st)):
            # clean-greedy exact repeat: the donor's materialized
            # first token IS argmax of this same logits row — no
            # pick, no draw, no sync (the greedy path never touches
            # the key stream, so skipping it is stream-exact too)
            st.pick = None
        else:
            st.first_cached = None
            first_lg = last[None, :]
            if bias_row is not None:
                first_lg = first_lg + bias_row
            if min_row is not None:
                first_lg = first_lg + min_row
            if st.gstart >= 0:
                # derived mask from the host table row (one V-float
                # build; admit is host-paced anyway)
                first_lg = first_lg + jnp.asarray(
                    (self._gtable_np[st.gstart] < 0).astype(np.float32)
                    * np.float32(-1e9))[None, :]
            st.pick = self._sample_dev(
                first_lg,
                np.asarray([st.temperature], np.float32),
                np.asarray([st.top_k or 0], np.int32),
                np.asarray([st.top_p], np.float32),
                np.asarray([st.min_p], np.float32),
                np.asarray([st.presence_penalty], np.float32),
                np.asarray([st.frequency_penalty], np.float32),
                np.asarray([st.repetition_penalty], np.float32),
                self._zero_vocab_row, seen_row,
                self.seeds[slot:slot + 1],
                self._seed_streams[slot:slot + 1],
                self._seed_on[slot:slot + 1],
                np.asarray([0], np.int32))
            if self._draws != draws_before:
                # the admit consumed a draw: this slot's own chain
                # moved
                self._slot_draws[slot] = 1
            if st.presence_penalty or st.frequency_penalty:
                self._counts = _zero_count_row(self._counts, slot)
                self._counts = _bump_one(self._counts, slot,
                                         st.pick[0])
            if rep_on:
                self._seen = _set_count_row(
                    self._seen, jnp.int32(slot), seen_row[0])
                self._seen = _bump_one(self._seen, slot, st.pick[0])
            if st.lp_n:
                st.pick_stats = _top_logprobs(
                    first_lg, jnp.asarray(st.pick, jnp.int32),
                    self.logprobs_k)
        st.spliced = True
        self._reserved[slot] = False
        # a window dispatched before this splice must not advance the
        # new slot's host mirrors at harvest (lens / draw chains were
        # just set HERE, for a window the slot sat out)
        if self._inflight_scan is not None:
            self._inflight_scan.skip.add(slot)

    def _finish_admit_resolve(self, st: AdmitState) -> int:
        """Host half of finish_admit: materialize the first-token pick
        (the admission's ONE blocking sync) and finish the host-side
        bookkeeping that needs its value."""
        slot = st.slot
        if st.plp_n:
            # host assembly: position 0 has no conditional (vLLM
            # emits null there); position j scores prompt[j] from
            # chunk (j-1)//c's row (j-1)%c
            c = self.chunk or st.t_p
            # ONE batched transfer for all chunks' stats: per-array
            # np.asarray would serialize a device round-trip per
            # chunk — painful for exactly the long prompts this
            # feature scores
            hosts = jax.device_get(st.plp_dev)
            recs: list = [None]
            for j in range(1, st.t_p):
                clp, tlp, tid = hosts[(j - 1) // c]
                r = (j - 1) % c
                recs.append((
                    float(clp[r]),
                    [(int(tid[r][q]), float(tlp[r][q]))
                     for q in range(st.plp_n)],
                ))
            self._prompt_lp[slot] = recs
        if st.pick is None:
            first = int(st.first_cached)
        else:
            first = int(np.asarray(st.pick)[0])
        if st.lp_n:
            clp, tlp, tid = st.pick_stats
            self._record_logprobs(slot, float(np.asarray(clp)[0]),
                                  np.asarray(tlp)[0], np.asarray(tid)[0])
        if st.gstart >= 0:
            self.gstate[slot] = int(self._gtable_np[st.gstart, first])
        if self._clean_greedy_admit(st):
            # make this slot a zero-sync donor for the next exact
            # repeat: the materialized greedy first token rides the
            # resident-prompt record
            rec = self._slot_prompts[slot]
            self._slot_prompts[slot] = rec[:4] + (first,)
        self.last_token[slot] = first
        self.outputs[slot] = [first]
        self._tokens += 1
        self._maybe_finish(slot, first)
        return slot

    @staticmethod
    def _clean_greedy_admit(st: AdmitState) -> bool:
        """Pure-greedy, unmasked admission: the first token is exactly
        argmax of the final prompt logits row — a host int that can be
        stored with the resident-prompt record and reused by the next
        exact repeat without a pick or a sync.  Any knob that bends
        the pick (sampling, penalties, bias, min_tokens floor,
        grammar) or needs its stats (logprobs) disqualifies both
        storing and reuse."""
        return (st.temperature == 0.0 and not (st.top_k or 0)
                and st.top_p == 1.0 and st.min_p == 0.0
                and st.presence_penalty == 0.0
                and st.frequency_penalty == 0.0
                and st.repetition_penalty == 1.0
                and not st.logit_bias and not st.min_tokens
                and st.gstart < 0 and not st.lp_n)

    def _pen_live(self) -> bool:
        """Any presence/frequency-penalized request live?  Gates the
        per-step histogram bumps so the common (unpenalized) engine
        does zero extra device work (knobs reset at finish)."""
        return bool(self.pres.any() or self.freqs.any())

    def _bias_live(self) -> bool:
        """Any ACTIVE slot with a logit_bias row — the gate for the
        pre-pick add (retired slots' rows are zero or their outputs
        discarded either way)."""
        return any(self._bias_on[s] for s in range(self.n_slots)
                   if self.active[s])

    def _grammar_live(self) -> bool:
        """Any ACTIVE slot under grammar constraint."""
        return bool(self._goffsets) and any(
            self.active[s] and self.gstate[s] >= 0
            for s in range(self.n_slots))

    def _min_live(self) -> bool:
        """Any ACTIVE slot still below its min_tokens floor."""
        return any(
            self.active[s]
            and len(self.outputs[s]) < int(self.min_toks[s])
            for s in range(self.n_slots))

    def _min_need(self) -> np.ndarray:
        """[S] float gate: 1 while the slot is below its floor."""
        return np.asarray(
            [float(len(self.outputs[s]) < int(self.min_toks[s]))
             for s in range(self.n_slots)], np.float32)

    def _rep_live(self) -> bool:
        return bool((self.reps != 1.0).any())

    def _record_logprobs(self, slot: int, chosen_lp: float,
                         top_lp, top_id) -> None:
        """Append one emitted token's stats, trimmed to the request's
        n: (chosen logprob, [(token id, logprob) x n])."""
        n = self._lp_want[slot]
        self._lp_records[slot].append((
            chosen_lp,
            [(int(top_id[j]), float(top_lp[j])) for j in range(n)],
        ))

    def _harvest_logprobs(self, clp, tlp, tid, eligible=None) -> None:
        """Record one decode step's [S]-wide logprob stats for every
        active slot that asked (host arrays).  *eligible* restricts to
        slots that were IN the scan (a mid-window admission's slot is
        active by harvest time but its scan row is garbage)."""
        for s in range(self.n_slots):
            if eligible is not None and not eligible[s]:
                continue
            if self.active[s] and self._lp_want[s]:
                self._record_logprobs(s, float(clp[s]), tlp[s], tid[s])

    def prompt_logprobs(self, slot: int):
        """Prompt-scoring records from admission (vLLM's
        ``prompt_logprobs``): entry 0 is None (no conditional), entry
        j is ``(logprob of prompt[j] given prompt[:j],
        [(token id, logprob) x n])``.  Empty unless the request asked.
        """
        return list(self._prompt_lp[slot])

    def token_logprobs(self, slot: int):
        """Per-token logprob records for *slot* (finished or in
        flight), parallel to :meth:`output`: a list of
        ``(chosen_logprob, [(token_id, logprob), ...])`` with the
        request's ``logprobs`` n entries each.  Empty when the request
        didn't ask."""
        return list(self._lp_records[slot])

    def _sample_dev(self, logits, temps, topks, topps, minps, pres,
                    freqs, reps, counts, seen, seeds, seed_streams,
                    seed_on, seed_idx):
        """:meth:`_sample` without the host materialization: returns
        the picked tokens as a DEVICE array (async dispatch).  Draw
        accounting is identical — the split admission path defers only
        the sync, never the key-stream bookkeeping."""
        if not _knobs_live(temps, topks, topps, minps, pres, freqs,
                           reps):
            # all-greedy batch (the default): plain argmax — no vocab
            # sort, no Gumbel draw, and the key stream stays untouched
            # so adding a sampled request never shifts greedy outputs
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        key = jax.random.fold_in(self._rng, self._draws)
        self._draws += 1
        seeded = bool(np.asarray(seed_on).any())
        return _pick_tokens(logits, jnp.asarray(temps),
                            jnp.asarray(topks),
                            jnp.asarray(topps), jnp.asarray(minps),
                            jnp.asarray(pres), jnp.asarray(freqs),
                            jnp.asarray(reps), counts, seen, key,
                            seeded, jnp.asarray(seeds),
                            jnp.asarray(seed_streams),
                            jnp.asarray(seed_on),
                            jnp.asarray(seed_idx))

    def _sample(self, logits, temps, topks, topps, minps, pres, freqs,
                reps, counts, seen, seeds, seed_streams, seed_on,
                seed_idx):
        return np.asarray(
            self._sample_dev(logits, temps, topks, topps, minps, pres,
                             freqs, reps, counts, seen, seeds,
                             seed_streams, seed_on, seed_idx),
            dtype=np.int32)

    # -- decoding ----------------------------------------------------------

    def _engine_extend(self, tokens, positions, aids):
        """One extend on the ENGINE cache (vs. the B=1 admission
        minis, which always run contiguous): the paged engine swaps in
        its paged model twin + block tables, everything else is the
        same compiled step."""
        if self._paged:
            logits, self.cache = extend_step(
                self._pmodel, self.params, self.cache, tokens,
                positions, aids, self._bt())
        else:
            logits, self.cache = extend_step(
                self.model, self.params, self.cache, tokens,
                positions, aids)
        return logits

    def step(self) -> Dict[int, int]:
        """One decode step for every active slot, each picking its
        next token with its own temperature/top-k (0/None = greedy).
        Returns {slot: token} for slots still active after the step."""
        if not any(self.active):
            return {}
        for s in range(self.n_slots):
            if self.active[s] and self.lens[s] >= self.model.max_len:
                self._finish(s)
        if not any(self.active):
            return {}
        self._ensure_append_pages(1)
        if not any(self.active):
            return {}  # the page-pressure policy preempted the rest
        tokens = jnp.asarray(self.last_token)[:, None]
        positions = jnp.asarray(self.lens, jnp.int32)[:, None]
        aids = (jnp.asarray(self.adapters)
                if self.model.n_adapters > 0 else None)
        logits = self._engine_extend(tokens, positions, aids)
        self._steps += 1
        sidx = np.asarray(self._slot_draws, np.int32)
        draws_before = self._draws
        lg = logits[:, -1, :]
        if self._bias_live():
            lg = lg + self._bias
        if self._min_live():
            lg = lg + self._min_mask * jnp.asarray(
                self._min_need())[:, None]
        grammared = self._grammar_live()
        if grammared:
            gs = jnp.asarray(np.maximum(self.gstate, 0))
            gon = jnp.asarray(
                (self.gstate >= 0).astype(np.float32))[:, None]
            grow = self._gtable[gs]
            lg = lg + jnp.where(grow < 0, -1e9, 0.0) * gon
        nxt = self._sample(lg, self.temps, self.topks,
                           self.topps, self.minps, self.pres,
                           self.freqs, self.reps, self._counts,
                           self._seen, self.seeds, self._seed_streams,
                           self._seed_on, sidx)
        if self._draws != draws_before:
            # a sampled step advances every slot's own chain in
            # lockstep (garbage rows are reset at their next admit)
            self._slot_draws = [d + 1 for d in self._slot_draws]
        if self._pen_live():
            self._counts = _bump_counts(self._counts, jnp.asarray(nxt))
        if self._rep_live():
            self._seen = _bump_counts(self._seen, jnp.asarray(nxt))
        if self.logprobs_k and any(
                self._lp_want[s] for s in range(self.n_slots)
                if self.active[s]):
            # lg carries the bias when live (OpenAI semantics: the
            # reported distribution is the one the pick used)
            clp, tlp, tid = _top_logprobs(
                lg, jnp.asarray(nxt), self.logprobs_k)
            self._harvest_logprobs(
                np.asarray(clp), np.asarray(tlp), np.asarray(tid))
        out = {}
        for s in range(self.n_slots):
            self.lens[s] += 1  # every slot appended (masking, not branching)
            if not self.active[s]:
                continue
            tok = int(nxt[s])
            if grammared and self.gstate[s] >= 0:
                self.gstate[s] = int(self._gtable_np[self.gstate[s], tok])
            self.last_token[s] = tok
            self.outputs[s].append(tok)
            self._tokens += 1
            out[s] = tok
            self._maybe_finish(s, tok)
        return out

    def run(self, max_steps: int) -> None:
        for _ in range(max_steps):
            if not any(self.active):
                return
            self.step()

    # -- speculative decoding ----------------------------------------------

    def spec_round(self) -> Dict[int, List[int]]:
        """One speculative round for every active slot: the draft
        proposes ``gamma`` tokens (one batched ``lax.scan``), the target
        verifies them in ONE ``[S, gamma+1]`` extend, and each slot
        commits its accepted prefix plus the target's own next token —
         1..gamma+1 tokens per slot for one host round-trip, tokens
        bit-identical to :meth:`step` greedy decoding.

        Greedy-only, like the first-mismatch acceptance rule it uses:
        raises if any active slot armed sampling knobs or logprobs
        (vLLM's speculative path has the same posture — rejection
        sampling is a different verifier).  Returns {slot: [tokens]}.
        """
        if self._draft_model is None and not self._ngram:
            raise RuntimeError(
                "engine was built without a speculative proposer "
                "(ServingEngine(..., draft=(model, params)) or "
                "draft=\"ngram\")")
        if _knobs_live(self.temps, self.topks, self.topps, self.minps,
                       self.pres, self.freqs, self.reps):
            raise ValueError(
                "speculative decoding is greedy-only: a slot armed "
                "sampling/penalty knobs")
        if self.logprobs_k and any(
                self._lp_want[s] for s in range(self.n_slots)
                if self.active[s]):
            raise ValueError(
                "speculative decoding does not produce per-token "
                "logprobs (the accepted tokens skip their own decode "
                "step)")
        if self._grammar_live():
            raise ValueError(
                "speculative decoding does not compose with grammar "
                "constraints (verify positions depend on sequential "
                "DFA states); decode grammar requests with "
                "step/run_scan")
        if not any(self.active):
            return {}
        for s in range(self.n_slots):
            if self.active[s] and self.lens[s] >= self.model.max_len:
                self._finish(s)
        if not any(self.active):
            return {}
        from .speculative import _draft_propose

        g = self.gamma
        headroom = min(self.model.max_len - self.lens[s]
                       for s in range(self.n_slots) if self.active[s])
        if headroom < g + 1:
            # a slot is too close to the cache end for the full verify
            # band: position max_len lands a CLAMPED write on row
            # max_len-1, overwriting that slot's valid tail K/V
            # mid-extend — decode the endgame with plain steps instead
            # (bit-identical to what the plain engine does there).
            # Draft caches go stale for tokens emitted this way; that
            # only costs accept rate on later rounds (the target verify
            # is ground truth), never token correctness.
            return {s: [t] for s, t in self.step().items()}
        self._ensure_append_pages(g + 1)
        if not any(self.active):
            return {}
        first = jnp.asarray(self.last_token)          # [S]
        pos0 = jnp.asarray(self.lens, jnp.int32)      # [S]
        if self._ngram:
            # host-side prompt-lookup proposals — histories are short
            # and resident (no device work until the verify)
            pnp = np.zeros((self.n_slots, g), np.int32)
            for s in range(self.n_slots):
                if not self.active[s]:
                    continue
                rec = self._slot_prompts[s]
                hist = np.concatenate([
                    rec[0] if rec is not None else
                    np.zeros(0, np.int32),
                    np.asarray(self.outputs[s], np.int32),
                ])
                pnp[s] = _ngram_propose(hist, self.ngram_n, g)
            props = jnp.asarray(pnp)
        else:
            props, self._draft_cache = _draft_propose(
                self._draft_model, self._draft_params, g,
                self._draft_cache, first, pos0)       # props [S, g]
        verify = jnp.concatenate([first[:, None], props], axis=1)
        positions = pos0[:, None] + jnp.arange(
            g + 1, dtype=jnp.int32)[None, :]
        aids = (jnp.asarray(self.adapters)
                if self.model.n_adapters > 0 else None)
        logits = self._engine_extend(verify, positions, aids)
        if self._bias_live():
            # logit_bias composes with greedy spec: the verify rule is
            # the SAME biased argmax plain decoding uses, so tokens
            # stay bit-identical (the draft proposes unbiased, which
            # only costs accept rate)
            logits = logits + self._bias[:, None, :]
        if self._min_live():
            # min_tokens: verify position j emits output token
            # (emitted + j), so the eos/stop mask lifts per position
            # exactly where plain decoding would
            emitted = jnp.asarray(
                [len(self.outputs[s]) for s in range(self.n_slots)],
                jnp.int32)
            gate = ((emitted[:, None]
                     + jnp.arange(g + 1, dtype=jnp.int32)[None, :])
                    < jnp.asarray(self.min_toks)[:, None]
                    ).astype(logits.dtype)
            logits = logits + self._min_mask[:, None, :] * gate[:, :, None]
        tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, g+1]
        # ONE batched transfer (per-array np.asarray would serialize
        # two blocking round-trips on the hot path this feature exists
        # to shorten)
        props_h, tgt_h = jax.device_get((props, tgt))
        self._steps += 1
        self._spec_rounds += 1

        out: Dict[int, List[int]] = {}
        new_lens = np.zeros(self.n_slots, np.int32)
        dispatched = np.asarray(self.active, bool)  # active at verify
        for s in range(self.n_slots):
            if not dispatched[s]:
                # host mirror only (step() does the same +1): the
                # DEVICE lens of a parked slot is deliberately left
                # alone by the rollback below — it sits high so writes
                # clamp past the slot's prompt K/V (the APC donor rows)
                self.lens[s] += g + 1
                continue
            acc = 0
            while acc < g and props_h[s, acc] == tgt_h[s, acc]:
                acc += 1
            self._spec_proposed += g
            self._spec_accepted += acc
            # committed = accepted proposals + the target's own token
            # (correction at the first mismatch / bonus on full
            # acceptance) == tgt_h[s, :acc+1]; cap at the cache end —
            # token j was computed at position lens+j, valid only
            # below max_len
            k = min(acc + 1, self.model.max_len - self.lens[s])
            toks = []
            for j in range(k):
                tok = int(tgt_h[s, j])
                self.last_token[s] = tok
                self.outputs[s].append(tok)
                self._tokens += 1
                toks.append(tok)
                self._maybe_finish(s, tok)
                if not self.active[s]:
                    # eos / stop / budget: later verify tokens are
                    # discarded, the cache rolls back to the real end
                    k = j + 1
                    break
            self.lens[s] += k
            new_lens[s] = self.lens[s]
            if self.active[s] and self.lens[s] >= self.model.max_len:
                self._finish(s)
            out[s] = toks
        # both caches roll to the SAME committed length: the target
        # keeps its accepted verify rows, the draft holds
        # [first, props[:-1]] plus the extra append (_draft_propose's
        # final extend), so rows < lens are valid in both.  Slots that
        # finished DURING the commit loop still get their exact lens
        # (dispatched mask, not self.active)
        self.cache = _rollback_active(self.cache, new_lens, dispatched)
        if self._draft_cache is not None:
            self._draft_cache = _rollback_active(
                self._draft_cache, new_lens, dispatched)
        return out

    def run_spec(self, max_rounds: int) -> None:
        """Speculative rounds until every slot retires (the spec-decode
        analog of :meth:`run`)."""
        for _ in range(max_rounds):
            if not any(self.active):
                return
            self.spec_round()

    @property
    def accept_rate(self) -> float:
        """Fraction of draft proposals the target kept (draft-quality
        metric, not a correctness knob)."""
        return (self._spec_accepted / self._spec_proposed
                if self._spec_proposed else 0.0)

    def spec_ready(self) -> bool:
        """Would :meth:`spec_round` run right now?  True iff a draft is
        loaded and no active slot armed sampling knobs or logprobs —
        the schedulers' predicate for adaptively switching between
        spec rounds (greedy traffic) and run_scan (mixed traffic)."""
        if self._draft_model is None and not self._ngram:
            return False
        if _knobs_live(self.temps, self.topks, self.topps, self.minps,
                       self.pres, self.freqs, self.reps):
            return False
        if self.logprobs_k and any(
                self._lp_want[s] for s in range(self.n_slots)
                if self.active[s]):
            return False
        if self._grammar_live():
            return False
        return True

    # -- structural jump-ahead (grammar-forced chains) ----------------------

    def _forced_chain(self, state: int, cap: int) -> List[int]:
        """Walk the DFA from *state* while exactly ONE token is legal;
        returns the forced tokens.  Stops at eos (an eos-only state
        retires via the normal pick — eos is -1e6-floorable data, not
        a chain link) and at *cap*."""
        chain: List[int] = []
        for _ in range(cap):
            row = self._gtable_np[state]
            allowed = np.flatnonzero(row >= 0)
            if allowed.size != 1:
                break
            t = int(allowed[0])
            if t == self.eos_id:
                break
            chain.append(t)
            state = int(row[t])
        return chain

    def jump_ready(self) -> bool:
        """Would :meth:`jump_round` run right now?  True iff a grammar
        slot is active and no active slot armed sampling knobs or
        logprobs (forced commits skip picks, so they consume no draws
        and record no logprobs — greedy-only, like spec_round)."""
        if not self._grammar_live():
            return False
        if _knobs_live(self.temps, self.topks, self.topps, self.minps,
                       self.pres, self.freqs, self.reps):
            return False
        if self.logprobs_k and any(
                self._lp_want[s] for s in range(self.n_slots)
                if self.active[s]):
            return False
        return True

    def forced_pending(self) -> bool:
        """Any active constrained slot whose NEXT token is forced (a
        single non-eos legal continuation)?  The scheduler's cheap
        trigger for :meth:`jump_round` — when nothing is forced, a
        jump commits exactly what a step would, at the wider extend's
        cost, so run_scan wins."""
        if not self.jump_ready():
            return False
        for s in range(self.n_slots):
            if self.active[s] and self.gstate[s] >= 0:
                row = self._gtable_np[self.gstate[s]]
                allowed = np.flatnonzero(row >= 0)
                if allowed.size == 1 and int(allowed[0]) != self.eos_id:
                    return True
        return False

    def jump_round(self) -> Optional[Dict[int, List[int]]]:
        """Structural jump-ahead for grammar-constrained decoding
        (xgrammar's jump-forward, on the batched engine): tokens the
        DFA FORCES — exactly one legal continuation, the JSON keys and
        punctuation guided decoding spends most of its steps on — are
        committed in ONE fixed-width ``[S, jump_len+1]`` extend
        instead of one decode step each, plus a masked-argmax bonus
        token from each slot's post-chain position.  1..jump_len+1
        tokens per slot for one host round-trip, bit-identical to
        :meth:`step` decoding: a forced token IS the greedy pick
        (every alternative sits at -1e9, which no logit, [-100, 100]
        bias, or -1e6 floor can overcome).

        Greedy-only (see :meth:`jump_ready`).  Returns None when the
        fixed extend band cannot run safely — a slot lacks jump_len+1
        rows of cache headroom, or a parked APC donor's prompt rows
        would sit inside the clamped write band — and the caller
        falls back to step()/run_scan().  Unconstrained (and
        unforced) active slots ride the same extend and commit their
        position-0 pick, exactly a step() commit."""
        if not self.jump_ready():
            raise ValueError(
                "jump_round needs grammar-live all-greedy traffic "
                "(jump_ready() is the predicate)")
        if not any(self.active):
            return {}
        for s in range(self.n_slots):
            if self.active[s] and self.lens[s] >= self.model.max_len:
                self._finish(s)
        if not any(self.active):
            return {}
        T = self.jump_len + 1
        headroom = min(self.model.max_len - self.lens[s]
                       for s in range(self.n_slots) if self.active[s])
        if headroom < T:
            return None  # endgame: clamped band would hit live rows
        for s in range(self.n_slots):
            # parked donors: the masked extend's clamped writes land on
            # rows [max_len - T, max_len - 1]; every parked prompt's
            # canon rows must sit strictly below (same invariant
            # spec_round's admit-time gamma bound enforces statically —
            # here T is jump-specific, so it is checked per round).
            # Only relevant while APC can read parked rows back.
            if (self.auto_prefix and not self.active[s]
                    and self._slot_prompts[s] is not None):
                if self._slot_prompts[s][2] > self.model.max_len - T:
                    return None
        chains: Dict[int, List[int]] = {}
        post = np.full(self.n_slots, -1, np.int32)
        for s in range(self.n_slots):
            if not self.active[s]:
                continue
            if self.gstate[s] >= 0:
                chains[s] = self._forced_chain(
                    int(self.gstate[s]), self.jump_len)
                st = int(self.gstate[s])
                for t in chains[s]:
                    st = int(self._gtable_np[st, t])
                post[s] = st
            else:
                chains[s] = []
        self._ensure_append_pages(T)
        if not any(self.active):
            return {}
        toks = np.zeros((self.n_slots, T), np.int32)
        toks[:, 0] = self.last_token
        for s, c in chains.items():
            if c:
                toks[s, 1:1 + len(c)] = c
        k = np.asarray([len(chains.get(s, ()))
                        for s in range(self.n_slots)], np.int32)
        positions = (jnp.asarray(self.lens, jnp.int32)[:, None]
                     + jnp.arange(T, dtype=jnp.int32)[None, :])
        aids = (jnp.asarray(self.adapters)
                if self.model.n_adapters > 0 else None)
        logits = self._engine_extend(jnp.asarray(toks), positions,
                                     aids)
        # bonus pick from each slot's post-chain position
        lg = jnp.take_along_axis(
            logits, jnp.asarray(k)[:, None, None], axis=1)[:, 0, :]
        if self._bias_live():
            lg = lg + self._bias
        if self._min_live():
            emitted = np.asarray(
                [len(self.outputs[s]) for s in range(self.n_slots)],
                np.int32)
            gate = ((emitted + k) < self.min_toks).astype(np.float32)
            lg = lg + self._min_mask * jnp.asarray(gate)[:, None]
        gon = jnp.asarray((post >= 0).astype(np.float32))[:, None]
        grow = self._gtable[jnp.asarray(np.maximum(post, 0))]
        lg = lg + jnp.where(grow < 0, -1e9, 0.0) * gon
        bonus = np.asarray(jnp.argmax(lg, axis=-1), np.int32)
        self._steps += 1
        self._jump_rounds += 1

        out: Dict[int, List[int]] = {}
        new_lens = np.zeros(self.n_slots, np.int32)
        dispatched = np.asarray(self.active, bool)
        for s in range(self.n_slots):
            if not dispatched[s]:
                self.lens[s] += T  # host mirror only (see spec_round)
                continue
            committed = chains[s] + [int(bonus[s])]
            toks_out = []
            n_c = len(committed)
            for j, tok in enumerate(committed):
                self.last_token[s] = tok
                self.outputs[s].append(tok)
                self._tokens += 1
                toks_out.append(tok)
                if self.gstate[s] >= 0:
                    self.gstate[s] = int(
                        self._gtable_np[self.gstate[s], tok])
                self._maybe_finish(s, tok)
                if not self.active[s]:
                    n_c = j + 1  # later tokens discarded
                    break
            self.lens[s] += n_c
            # forced-token accounting from the COMMITTED prefix (a
            # stop/budget finish mid-chain discards the rest; counting
            # dispatch would overstate jump savings)
            self._jump_forced += min(n_c, len(chains[s]))
            new_lens[s] = self.lens[s]
            if self.active[s] and self.lens[s] >= self.model.max_len:
                self._finish(s)
            out[s] = toks_out
        self.cache = _rollback_active(self.cache, new_lens, dispatched)
        # the draft cache (if any) is deliberately untouched: like
        # step(), a jump leaves it stale, which only costs accept rate
        # on later spec rounds (the target verify is ground truth)
        return out

    def run_scan(self, n_steps: int) -> Dict[int, List[int]]:
        """*n_steps* decode steps as ONE compiled ``lax.scan`` — no
        per-token host round-trip (the difference is decisive over
        remote/tunneled transports, same reason greedy_generate scans).
        Token-for-token identical to ``n_steps`` × :meth:`step` when no
        admissions interleave; EOS/budget retirement applies AFTER the
        scan (retired slots' extra tokens are computed and discarded —
        masking, not branching — exactly like inactive slots in
        ``step``).  Every active slot must have *n_steps* of cache
        headroom.  Returns {slot: [tokens]} for slots active at entry.

        Equal to ``scan_harvest(scan_dispatch(n_steps))`` — the split
        form is what the iteration scheduler uses to slide prefill
        chunks and admission finishes inside the open window."""
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        if not any(self.active):
            return {}
        return self.scan_harvest(self.scan_dispatch(n_steps))

    def scan_dispatch(self, n_steps: int) -> _ScanHandle:
        """Dispatch *n_steps* decode steps as one compiled scan and
        return WITHOUT waiting for the device: the handle carries the
        window's device futures plus a snapshot of who was in it.
        Between dispatch and :meth:`scan_harvest` the host may run
        admission work (prefill chunks, splices, first-token picks) —
        all async dispatches that overlap the window's device time —
        but no other decode path (one window outstanding at most)."""
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        if self._inflight_scan is not None:
            raise RuntimeError(
                "a dispatched window is already outstanding "
                "(scan_harvest it first)")
        if not any(self.active):
            raise RuntimeError("no active slots to scan")
        for s in range(self.n_slots):
            if self.active[s] and \
                    self.lens[s] + n_steps > self.model.max_len:
                raise ValueError(
                    f"slot {s} has {self.model.max_len - self.lens[s]} "
                    f"cache rows left, need {n_steps}")
        self._ensure_append_pages(n_steps)
        if not any(self.active):
            raise RuntimeError(
                "page-pressure policy preempted every active slot")
        sampled = _knobs_live(self.temps, self.topks, self.topps,
                              self.minps, self.pres, self.freqs,
                              self.reps)
        pen = self._pen_live()
        rep = self._rep_live()
        seeded = bool(self._seed_on.any())
        # logprob stats ride the scan only when someone is listening:
        # at most two compiled variants (k and 0), never per request
        lp_k = self.logprobs_k if any(
            self._lp_want[s] for s in range(self.n_slots)
            if self.active[s]) else 0
        if self._knob_cache is None:
            # rebuild the device mirrors once per admit/retire burst
            # instead of once per window (values change only there)
            self._knob_cache = (
                jnp.asarray(self.temps), jnp.asarray(self.topks),
                jnp.asarray(self.topps), jnp.asarray(self.minps),
                jnp.asarray(self.pres), jnp.asarray(self.freqs),
                jnp.asarray(self.reps), jnp.asarray(self.min_toks),
                jnp.asarray(self.seeds),
                jnp.asarray(self._seed_streams),
                jnp.asarray(self._seed_on),
                (jnp.asarray(self.adapters)
                 if self.model.n_adapters > 0 else None),
            )
        (temps_d, topks_d, topps_d, minps_d, pres_d, freqs_d, reps_d,
         min_toks_d, seeds_d, streams_d, seed_on_d,
         aids) = self._knob_cache
        biased = self._bias_live()
        minned = self._min_live()
        grammared = self._grammar_live()
        if grammared:
            gtable = self._gtable
        else:
            # unused placeholder (the static flag gates its use); a
            # tiny fixed shape keeps the jit cache key stable
            gtable = jnp.zeros((1, 1), jnp.int32)
        fused = self.fused_decode
        if fused:
            stop_mat, eos_vec = self._build_fused_vectors()
            budget = jnp.int32(
                self.max_new_tokens if self.max_new_tokens is not None
                else _NO_BUDGET)
        else:
            stop_mat = eos_vec = budget = None
        ys, self.cache, self._counts, self._seen, fin, frs = _scan_decode(
            self._pmodel if self._paged else self.model,
            n_steps, sampled, lp_k, pen, rep, seeded,
            biased, minned, grammared, fused, self.params, self.cache,
            jnp.asarray(self.last_token), jnp.asarray(self.lens, jnp.int32),
            temps_d, topks_d,
            topps_d, minps_d,
            pres_d, freqs_d,
            reps_d, self._counts, self._seen,
            self._bias, self._min_mask, min_toks_d,
            jnp.asarray([len(self.outputs[s])
                         for s in range(self.n_slots)], jnp.int32),
            gtable, jnp.asarray(self.gstate),
            seeds_d, streams_d,
            seed_on_d,
            jnp.asarray(self._slot_draws, jnp.int32), aids,
            self._rng, jnp.int32(self._draws),
            self._bt() if self._paged else None,
            stop_mat=stop_mat, eos_vec=eos_vec, budget=budget,
        )
        handle = _ScanHandle(ys, n_steps, sampled, lp_k, grammared,
                             list(self.active), fused=fused,
                             fin=fin, frs=frs)
        self._inflight_scan = handle
        return handle

    def _build_fused_vectors(self):
        """Device mirrors for the fused boundary detector: a padded
        per-slot stop-id matrix [S, K] (-1 padding never matches a
        real token) and the effective eos vector [S] (-1 where eos is
        None or the slot opted out via ignore_eos).  K is the max stop
        set size rounded up to a multiple of ``_STOP_PAD`` so stop-set
        churn re-specializes the jit at coarse width steps, not per
        admission.  Cached like ``_knob_cache`` but with its own
        invalidation: a knob-identical admission can still change
        stops / ignore_eos (see _finish_admit_dispatch)."""
        if self._fused_cache is None:
            widest = max(
                (len(self._stops[s]) for s in range(self.n_slots)),
                default=0)
            K = max(_STOP_PAD, -(-widest // _STOP_PAD) * _STOP_PAD)
            mat = np.full((self.n_slots, K), -1, np.int32)
            for s in range(self.n_slots):
                for j, t in enumerate(sorted(self._stops[s])):
                    mat[s, j] = t
            eos = -1 if self.eos_id is None else int(self.eos_id)
            eos_vec = np.asarray(
                [-1 if self._ignore_eos[s] else eos
                 for s in range(self.n_slots)], np.int32)
            self._fused_cache = (jnp.asarray(mat),
                                 jnp.asarray(eos_vec))
        return self._fused_cache

    def scan_abandon(self, handle: _ScanHandle) -> None:
        """Drop a dispatched-but-unharvested window WITHOUT its host
        bookkeeping (the crash-supervisor / supersede path when a
        dispatch-ahead window is outstanding).  The device futures are
        discarded; the affected slots' cache state is suspect — the
        caller releases every slot, exactly as it does after any other
        mid-iteration crash."""
        if self._inflight_scan is handle:
            self._inflight_scan = None

    def scan_harvest(self, handle: _ScanHandle) -> Dict[int, List[int]]:
        """Materialize a dispatched window's tokens (the window's ONE
        blocking sync) and run the host bookkeeping for every slot that
        was IN the window.  Slots spliced after the dispatch
        (``handle.skip``) keep the lens / draw-chain values their
        finish_admit just set — they sat the window out."""
        self._inflight_scan = None
        ys, n_steps = handle.ys, handle.n_steps
        sampled, lp_k = handle.sampled, handle.lp_k
        grammared = handle.grammared
        skip = handle.skip
        # "in the window AND not yet retired AND not skip" — with no
        # mid-window admissions this is exactly the dispatch-time
        # active set, so run_scan behaves as it always did.  A skip
        # slot sat the window out BY DEFINITION: under dispatch-ahead
        # overlap a slot can be released and RE-admitted while the
        # window runs (active at dispatch AND active now, but the
        # column belongs to the old occupant), so membership in skip —
        # not the active snapshots — is what excludes its tokens
        live = [handle.active[s] and self.active[s] and s not in skip
                for s in range(self.n_slots)]
        toks = np.asarray(ys[0], dtype=np.int32)  # [n_steps, S]
        if lp_k:
            clps = np.asarray(ys[1])   # [n_steps, S]
            tlps = np.asarray(ys[2])   # [n_steps, S, k]
            tids = np.asarray(ys[3])   # [n_steps, S, k]
        self._steps += n_steps
        out: Dict[int, List[int]] = {
            s: [] for s in range(self.n_slots) if live[s]
        }
        if handle.fused:
            return self._harvest_fused(
                handle, live, toks,
                clps if lp_k else None, tlps if lp_k else None,
                tids if lp_k else None, out)
        if not sampled and not lp_k and not grammared:
            # greedy/unconstrained harvest fast path (the serving hot
            # path): nothing sampled means no draw accounting, no
            # logprob harvest, no DFA walk — each slot's column
            # processes at C speed instead of one Python branch pass
            # per token per step.  Semantics identical to the general
            # loop below (_maybe_finish checks per token in eos >
            # stop > budget order; ties resolve the same way here
            # because the stop scan excludes the eos index and the
            # budget cut only applies strictly before any eos/stop).
            for s in range(self.n_slots):
                if s not in skip:
                    self.lens[s] += n_steps
            eos = None if self.eos_id is None else int(self.eos_id)
            for s in list(out):
                col = toks[:, s].tolist()
                fin = None  # (index, reason), earliest token wins
                if eos is not None and not self._ignore_eos[s]:
                    try:
                        fin = (col.index(eos), "eos")
                    except ValueError:
                        pass
                stops = self._stops[s]
                if stops:
                    for i, t in enumerate(
                            col if fin is None else col[:fin[0]]):
                        if t in stops:
                            fin = (i, "stop")
                            break
                if self.max_new_tokens is not None:
                    room = self.max_new_tokens - len(self.outputs[s])
                    if room <= n_steps and (
                            fin is None or room - 1 < fin[0]):
                        fin = (room - 1, "length")
                kept = col if fin is None else col[:fin[0] + 1]
                self.outputs[s].extend(kept)
                out[s] = kept
                self._tokens += len(kept)
                if kept:
                    self.last_token[s] = kept[-1]
                if fin is not None:
                    self._finish(s, fin[1])
            return out
        # mirror step()'s draw accounting: a draw is consumed only
        # while some sampled slot is still live (retirement resets
        # its knobs, re-arming the greedy fast path), so the key
        # stream a later admission sees is identical whichever
        # scheduling API ran this window — the scan's keys for
        # post-retirement steps produced only discarded tokens.  The
        # liveness check is an ARMED SET snapshotted once at harvest
        # entry, not a per-step full-vector recompute: between harvest
        # steps the only knob mutator is _finish -> _reset_slot_params,
        # so the set can only shrink, and exactly when a slot finishes.
        # Mid-window admissions' knobs must not leak into the window's
        # draw accounting (their vectors were armed AFTER the
        # dispatch), so skip slots never enter the set.
        armed: set = set()
        if sampled:
            lv = _knobs_live_vec(self.temps, self.topks, self.topps,
                                 self.minps, self.pres, self.freqs,
                                 self.reps)
            armed = {s for s in range(self.n_slots)
                     if lv[s] and s not in skip}
        draws_used = 0
        for i in range(n_steps):
            if sampled and armed:
                draws_used += 1
            if lp_k:
                self._harvest_logprobs(
                    clps[i], tlps[i], tids[i],
                    eligible=[handle.active[s] and s not in skip
                              for s in range(self.n_slots)])
            for s in range(self.n_slots):
                if s not in skip:
                    self.lens[s] += 1
                if s in skip or not (handle.active[s]
                                     and self.active[s]):
                    continue
                tok = int(toks[i, s])
                if grammared and self.gstate[s] >= 0:
                    # host mirror of the carry's transitions, walked
                    # over the SAME emitted tokens
                    self.gstate[s] = int(
                        self._gtable_np[self.gstate[s], tok])
                self.last_token[s] = tok
                self.outputs[s].append(tok)
                self._tokens += 1
                out[s].append(tok)
                self._maybe_finish(s, tok)
                if not self.active[s]:
                    armed.discard(s)
        self._draws += draws_used
        # per-slot chains advance in lockstep with the global counter
        # (step() does the same once per sampled call); mid-window
        # admissions keep the chain finish_admit just reset
        self._slot_draws = [
            d if s in skip else d + draws_used
            for s, d in enumerate(self._slot_draws)]
        # lens advanced n_steps per slot in-device; the loop above
        # advanced the host mirror the same amount
        return out

    def _harvest_fused(self, handle: _ScanHandle, live, toks,
                       clps, tlps, tids,
                       out: Dict[int, List[int]]) -> Dict[int, List[int]]:
        """Columnar harvest for a fused window: the device already
        found each slot's first eos/stop/budget boundary (the scan's
        fin/frs carry), so the host slices kept prefixes instead of
        re-scanning columns token by token.  Every bookkeeping effect
        — outputs, lens, grammar-state mirror, logprob records, draw
        accounting, finish order — reproduces what the unfused path
        (greedy fast path or general loop) would have done for the
        same window, which is what the fused toggle matrix pins."""
        n_steps, skip = handle.n_steps, handle.skip
        sampled, lp_k = handle.sampled, handle.lp_k
        grammared = handle.grammared
        fin = np.asarray(handle.fin, np.int32)  # [S] first boundary
        frs = np.asarray(handle.frs, np.int32)  # [S] reason code
        self._fused_windows += 1
        # lens advance by the full window for every non-skip slot (the
        # device columns DID run n_steps; truncation is output-side,
        # exactly like the unfused paths)
        for s in range(self.n_slots):
            if s not in skip:
                self.lens[s] += n_steps
        live_idx = [s for s in range(self.n_slots) if live[s]]
        # kept-prefix length per live column (fin == -1: no boundary)
        keep = {s: (int(fin[s]) + 1 if fin[s] >= 0 else n_steps)
                for s in live_idx}
        self._fused_truncated += sum(
            n_steps - keep[s] for s in live_idx)
        # draw accounting BEFORE any finish resets knobs: the unfused
        # loop consumes one draw per step while any armed (knob-live,
        # non-skip) slot is still live, and an armed slot leaves the
        # set right after its finish step — so the step count is the
        # max kept-prefix length over the armed set
        draws_used = 0
        if sampled:
            lv = _knobs_live_vec(self.temps, self.topks, self.topps,
                                 self.minps, self.pres, self.freqs,
                                 self.reps)
            draws_used = max(
                (keep[s] for s in live_idx
                 if lv[s] and s not in skip), default=0)
        if grammared:
            # batched DFA walk: one fancy-indexed gather per step over
            # the columns still emitting, instead of a Python branch
            # per (step, slot).  gs can go negative mid-walk (an
            # in-grammar eos pick), which drops the column like the
            # per-token ``gstate >= 0`` guard does.
            gs = self.gstate
            for i in range(n_steps):
                cols = np.asarray(
                    [s for s in live_idx
                     if keep[s] > i and gs[s] >= 0], np.int64)
                if cols.size == 0:
                    break
                gs[cols] = self._gtable_np[gs[cols], toks[i, cols]]
        if lp_k:
            # bulk column materialization (tolist converts the whole
            # kept prefix at C speed) feeding the same per-token record
            # shape _record_logprobs appends
            for s in live_idx:
                n = self._lp_want[s]
                if not n:
                    continue
                k = keep[s]
                cl = clps[:k, s].tolist()
                tl = tlps[:k, s, :n].tolist()
                ti = tids[:k, s, :n].tolist()
                self._lp_records[s].extend(
                    (cl[i], list(zip(ti[i], tl[i])))
                    for i in range(k))
        for s in live_idx:
            kept = toks[:keep[s], s].tolist()
            self.outputs[s].extend(kept)
            out[s] = kept
            self._tokens += len(kept)
            if kept:
                self.last_token[s] = kept[-1]
        # finish order matters: _reset_slot_params stamps the parked-
        # donor LRU counter, so fused must retire slots in the same
        # order the unfused path would have — slot order on the greedy
        # fast path, (finish step, slot) order in the general loop
        finishing = [s for s in live_idx if fin[s] >= 0]
        if sampled or lp_k or grammared:
            finishing.sort(key=lambda s: (int(fin[s]), s))
        reasons = {1: "eos", 2: "stop", 3: "length"}
        for s in finishing:
            self._finish(s, reasons[int(frs[s])])
        if sampled:
            self._draws += draws_used
            self._slot_draws = [
                d if s in skip else d + draws_used
                for s, d in enumerate(self._slot_draws)]
        return out

    # -- completion --------------------------------------------------------

    def _maybe_finish(self, slot: int, token: int) -> None:
        if (self.eos_id is not None and token == self.eos_id
                and not self._ignore_eos[slot]):
            self._finish(slot, "eos")
        elif token in self._stops[slot]:
            self._finish(slot, "stop")
        elif (self.max_new_tokens is not None
              and len(self.outputs[slot]) >= self.max_new_tokens):
            self._finish(slot, "length")

    def _finish(self, slot: int, reason: str = "length") -> None:
        self._finished[slot] = self.outputs[slot]
        self._finish_reason[slot] = reason
        self.active[slot] = False
        self._completed += 1
        self._reset_slot_params(slot)

    def finished(self, slot: int) -> bool:
        return slot in self._finished

    def finish_reason(self, slot: int) -> Optional[str]:
        """Why the slot finished: "eos", "stop" (a per-request stop
        token), or "length" (budget/cache exhaustion); None while the
        request is still in flight (vLLM's finish_reason taxonomy)."""
        return self._finish_reason.get(slot)

    def output(self, slot: int) -> List[int]:
        """Generated tokens for *slot* (finished or in flight)."""
        return list(self.outputs[slot])

    def stats(self) -> Dict[str, int]:
        """Engine counters for the debug/observability endpoint:
        slot occupancy, total emitted tokens, decode steps taken."""
        out = {
            "n_slots": self.n_slots,
            "active_slots": sum(self.active),
            "free_slots": self.n_slots - sum(self.active),
            "reserved_slots": sum(self._reserved),
            "finished_requests": self._completed,
            "registered_prefixes": len(self._prefixes),
            "tokens_emitted": self._tokens,
            "decode_steps": self._steps,
            "prefill_tokens": self._prefill_tokens,
            "prefix_cache_hits": self._prefix_hits,
            "prefix_reused_tokens": self._prefix_reused_tokens,
            "spec_rounds": self._spec_rounds,
            "spec_proposed": self._spec_proposed,
            "spec_accepted": self._spec_accepted,
            "jump_rounds": self._jump_rounds,
            "jump_forced_tokens": self._jump_forced,
            "prefix_evictions": self._prefix_evictions,
            "packed_prefill_extends": self._packed_extends,
            "packed_prefill_rows": self._packed_rows,
            "packed_prefill_requests": self._packed_requests,
            "packed_prefill_pad_tokens": self._packed_pad_tokens,
            "fused_windows": self._fused_windows,
            "fused_truncated_tokens": self._fused_truncated,
        }
        if self._paged:
            assert self._pool is not None
            out.update(self._pool.stats())
            out["kv_preemptions"] = self._kv_preemptions
            out["kv_sessions_parked"] = len(self.session_slots())
        return out

    def release(self, slot: int) -> None:
        """Free a slot (abandons any in-flight generation)."""
        if self._inflight_scan is not None:
            # released while a dispatched window is open (possible only
            # under the scheduler's dispatch-ahead overlap): harvest
            # must not advance lens/chains release just reset — the
            # slot sat the rest of the window out, same contract as a
            # mid-window splice
            self._inflight_scan.skip.add(slot)
        self.active[slot] = False
        self._finished.pop(slot, None)
        self._finish_reason.pop(slot, None)
        self.lens[slot] = 0
        # _slot_prompts[slot] deliberately SURVIVES release: the prompt
        # K/V rows [0, canon) stay valid donors for automatic-prefix
        # matches until the slot is re-admitted (the common server
        # pattern: retire request A, admit request B sharing A's system
        # prompt into the same slot).  Validity rests on the clamped-
        # write invariant asserted in admit(): inactive slots' masked
        # decode writes land at device cache_lens rows clamped to
        # max_len - 1 — or max_len - gamma - 1 under a speculative
        # proposer, whose verify extend writes gamma+1 rows per round;
        # admit enforces the matching stronger prompt bound — and every
        # prompt row sits below the clamp band, so a parked slot's
        # prompt K/V is never overwritten.
        self._reset_slot_params(slot)

    def _reset_slot_params(self, slot: int) -> None:
        """Clear a freed slot's sampling/adapter knobs: the all-greedy
        argmax fast path gates on the WHOLE temps/topks vectors, so a
        finished sampled request must not keep disabling it."""
        self.temps[slot] = 0.0
        self.topks[slot] = 0
        self.topps[slot] = 1.0
        self.minps[slot] = 0.0
        self.pres[slot] = 0.0
        self.freqs[slot] = 0.0
        self.reps[slot] = 1.0
        self.adapters[slot] = -1
        self._stops[slot] = frozenset()
        self._ignore_eos[slot] = False
        self._seed_on[slot] = 0
        self._lp_want[slot] = 0  # records stay readable post-finish
        self._knob_cache = None  # device mirrors are stale now
        self._fused_cache = None  # stop/eos rows changed with them
        # parked-donor LRU stamp: under pool pressure the OLDEST
        # parked record's pages are reclaimed first
        self._park_counter += 1
        self._park_seq[slot] = self._park_counter
