"""JAX/XLA example workloads for the TPU device plugin.

The reference ships TF/vLLM GPU workloads as proof the plugin works
(/root/reference/example/pod/alexnet-gpu.yaml:16 runs
``tf_cnn_benchmarks.py --model=alexnet``); these are their TPU-native
replacements: an AlexNet image-classification benchmark written for the
MXU (bf16 matmuls/convs, static shapes, jit-compiled train step) and a
sharded variant that scales over a ``jax.sharding.Mesh``.
"""

from .alexnet import AlexNet, create_train_state, train_step
from .convpool import conv_pool
from .flash_attention import flash_attention, flash_causal_attention
from .inference import (
    DecodeTransformerLM,
    attach_lora,
    decode_throughput,
    greedy_generate,
    make_decoder,
    quantize_lm_params,
    quantize_lm_params_int4,
    sample_generate,
)
try:  # checkpointing needs orbax; the rest of the workloads don't
    from . import checkpoint
except ImportError:  # pragma: no cover - orbax always in the CI image
    checkpoint = None
from . import llama
from .moe import MoEFFN, top_k_routing
from .pool import max_pool as pallas_max_pool
from .server import EngineServer
from .grammar import TokenDfa, regex_to_dfa, token_dfa
from .serving import ServingEngine
from .speculative import speculative_generate
from .parallel import make_mesh, make_sharded_train_step
from .pipeline import make_pipeline, stack_layer_params
from .ring_attention import (
    full_attention,
    make_ring_attention,
    zigzag_permute,
    zigzag_unpermute,
)
from .transformer import TransformerLM, make_lm_mesh, make_lm_train_step

__all__ = [
    "AlexNet",
    "DecodeTransformerLM",
    "MoEFFN",
    "TransformerLM",
    "conv_pool",
    "create_train_state",
    "decode_throughput",
    "EngineServer",
    "flash_attention",
    "flash_causal_attention",
    "full_attention",
    "greedy_generate",
    "make_decoder",
    "quantize_lm_params",
    "quantize_lm_params_int4",
    "sample_generate",
    "ServingEngine",
    "TokenDfa",
    "regex_to_dfa",
    "token_dfa",
    "attach_lora",
    "checkpoint",
    "llama",
    "pallas_max_pool",
    "speculative_generate",
    "make_lm_mesh",
    "make_lm_train_step",
    "make_mesh",
    "make_pipeline",
    "make_ring_attention",
    "make_sharded_train_step",
    "stack_layer_params",
    "top_k_routing",
    "train_step",
    "zigzag_permute",
    "zigzag_unpermute",
]
