# tpulint: deterministic-path
"""Three-tier session KV store: device pages → host RAM → disk.

A chat fleet is mostly *idle* conversations.  The serving engine can
park a finished request's KV pages in its slot (``park_session``), but
device pages and slots are the scarcest resource in the system — so
this module runs the tiering policy that turns parked slots into a
session-scale durability contract:

- **device** — the slot itself: pages mapped, record resident, a
  returning request warm-resumes through the automatic prefix match
  with zero data movement.
- **host** — a bounded RAM pool of ``demote_session()`` checkpoints
  (storage-exact raw KV + tokens).  Idle or pressured device sessions
  demote here; a returning session promotes back with one scatter.
- **disk** — a crash-safe spill directory of migrate-codec payloads.
  Files are written ``tmp → os.replace`` atomic (a final-named file is
  complete by construction; the codec's length-checked container
  rejects truncation), pruned newest-K, and *survive process death*:
  a respawned replica rehydrates spilled sessions lazily on first
  touch, so a SIGKILL no longer destroys conversations.

Demotions ride seeded-jitter idle timers (one ``random.Random(seed)``
— the D1 deterministic-path discipline; callers inject ``now_s``) plus
page/slot pressure.  Every transition is wrapped in the PR-5
resilience layer: RetryPolicy on disk I/O, a watchdog on disk-tier
promotion fetches, a circuit breaker on a sick disk, and the
``suppressed()`` contract on every boundary — **a tiering failure must
never fail the request**; the worst case is a cold re-prefill.
``kv.demote`` / ``kv.promote`` / ``kv.spill`` fault hooks make every
one of those paths provokable from ``--fault-spec``.

Engine calls (park / demote / resume / discard) are scheduler-thread
only; HTTP handler threads use :meth:`export_session` /
:meth:`import_payload`, which touch the engine solely through a
command queue serviced by :meth:`tick`.
"""

from __future__ import annotations

import hashlib
import logging
import os
import random
import threading
import time
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Tuple

from ..resilience import faults
from ..resilience.policy import (
    CircuitBreaker,
    CircuitOpenError,
    ResilienceMetrics,
    RetryPolicy,
    Watchdog,
    suppressed,
)
from .migrate import MigrateError, dump_payload, load_payload

if TYPE_CHECKING:  # typing only: keep the runtime import graph lean
    from tpu_k8s_device_plugin.obs import FlightRecorder, Registry

log = logging.getLogger(__name__)

TIERS = ("device", "host", "disk")

# spill filename: <sha1(session_id)[:20]>-<seq:08d>.kvs — the hash keys
# the session without leaking its raw id into the filesystem, the seq
# makes every spill a fresh name (os.replace within one name, newest-K
# GC across names)
_SPILL_SUFFIX = ".kvs"


def sid_hash(session_id: str) -> str:
    return hashlib.sha1(session_id.encode("utf-8")).hexdigest()[:20]


def _state_nbytes(obj: object) -> int:
    """Approximate host bytes held by a checkpoint state (arrays
    dominate; scalars are noise)."""
    n = getattr(obj, "nbytes", None)
    if isinstance(n, int):
        return n
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if isinstance(obj, dict):
        return sum(_state_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_state_nbytes(v) for v in obj)
    return 0


class TierMetrics:
    """The ``tpu_kv_tier_*`` families on one obs registry."""

    def __init__(self, registry: "Registry") -> None:
        self.occupancy = registry.gauge(
            "tpu_kv_tier_occupancy",
            "Sessions currently resident per KV tier.", ("tier",))
        self.hits = registry.counter(
            "tpu_kv_tier_hits_total",
            "Returning-session warm hits by the tier that served "
            "them.", ("tier",))
        self.demotions = registry.counter(
            "tpu_kv_tier_demotions_total",
            "Session demotions by destination tier and reason.",
            ("tier", "reason"))
        self.promotions = registry.counter(
            "tpu_kv_tier_promotions_total",
            "Session promotion attempts by source tier and outcome "
            "(ok / degraded — degraded falls back to re-prefill).",
            ("tier", "outcome"))
        self.resume_seconds = registry.histogram(
            "tpu_kv_tier_resume_seconds",
            "Warm-resume latency (checkpoint fetch + scatter) by "
            "source tier.", ("tier",))
        self.spill_bytes = registry.gauge(
            "tpu_kv_tier_spill_bytes",
            "Bytes of session checkpoints resident in the disk tier.")
        self.evictions = registry.counter(
            "tpu_kv_tier_evictions_total",
            "Sessions evicted from the store (KV dropped, next visit "
            "re-prefills) by reason.", ("reason",))


class _Entry:
    """One tracked session (device or host tier; disk rides the
    filename index so it survives the process)."""

    __slots__ = ("sid", "tier", "slot", "state", "nbytes", "deadline",
                 "seq")

    def __init__(self, sid: str, tier: str, *, slot: int = -1,
                 state: Optional[Dict[str, object]] = None,
                 nbytes: int = 0, deadline: float = 0.0,
                 seq: int = 0) -> None:
        self.sid = sid
        self.tier = tier
        self.slot = slot
        self.state = state
        self.nbytes = nbytes
        self.deadline = deadline
        self.seq = seq


class _ExportReq:
    """A handler-thread request for a device-tier checkpoint, serviced
    on the scheduler thread by :meth:`SessionStore.tick`."""

    __slots__ = ("sid", "done", "payload", "error")

    def __init__(self, sid: str) -> None:
        self.sid = sid
        self.done = threading.Event()
        self.payload: Optional[bytes] = None
        self.error: Optional[str] = None


class SessionStore:
    """The tiering policy over one engine's parked sessions.

    All public entry points are no-raise (``suppressed()`` contract)
    except :meth:`export_session` / :meth:`import_payload`, whose
    callers translate errors to HTTP statuses."""

    def __init__(self, engine: Any, *,
                 spill_dir: Optional[str] = None,
                 host_cap_bytes: int = 256 * 1024 * 1024,
                 disk_keep: int = 512,
                 device_idle_s: float = 30.0,
                 host_idle_s: float = 120.0,
                 seed: int = 0,
                 registry: Optional["Registry"] = None,
                 recorder: Optional["FlightRecorder"] = None,
                 rmetrics: Optional[ResilienceMetrics] = None,
                 logger: Optional[logging.Logger] = None) -> None:
        self._engine = engine
        self._dir = spill_dir
        self.host_cap_bytes = host_cap_bytes
        self.disk_keep = disk_keep
        self.device_idle_s = device_idle_s
        self.host_idle_s = host_idle_s
        self._rng = random.Random(seed)
        self._recorder = recorder
        self._rmetrics = rmetrics
        self._log = logger or log
        self._m = TierMetrics(registry) if registry is not None else None
        self._lock = threading.RLock()
        self._entries: Dict[str, _Entry] = {}
        self._host_bytes = 0
        self._seq = 0
        # disk index: sid-hash -> (path, seq, nbytes); lazily rebuilt
        # from filenames at construction, which is how a respawned
        # generation inherits its predecessor's spilled sessions
        self._disk: Dict[str, Tuple[str, int, int]] = {}
        self._exports: List[_ExportReq] = []
        self._stale_slots: List[int] = []
        self._hit_counts = {t: 0 for t in TIERS}
        self._demote_count = 0
        self._promote_count = 0
        self._evict_count = 0
        self._retry = RetryPolicy(max_attempts=3, initial_backoff_s=0.05,
                                  max_backoff_s=0.5, seed=seed)
        self._breaker = CircuitBreaker("kv.disk", failure_threshold=3,
                                       reset_timeout_s=10.0,
                                       metrics=rmetrics,
                                       recorder=recorder)
        self._watchdog = Watchdog("kv.promote", timeout_s=10.0,
                                  metrics=rmetrics, recorder=recorder)
        if self._dir:
            try:
                os.makedirs(self._dir, exist_ok=True)
                self._scan_disk()
            except OSError as e:
                suppressed("kv_tier.scan", e, self._log, self._rmetrics)
        self._refresh_gauges()

    # -- bookkeeping -------------------------------------------------------

    def _jittered(self, now_s: float, idle_s: float) -> float:
        # seeded jitter de-synchronizes demotion herds across sessions
        # while keeping replays deterministic
        return now_s + idle_s * (0.9 + 0.2 * self._rng.random())

    def _scan_disk(self) -> None:
        assert self._dir is not None
        for name in os.listdir(self._dir):
            if not name.endswith(_SPILL_SUFFIX):
                continue
            stem = name[:-len(_SPILL_SUFFIX)]
            head, _, tail = stem.rpartition("-")
            if not head or not tail.isdigit():
                continue
            path = os.path.join(self._dir, name)
            try:
                nbytes = os.path.getsize(path)
            except OSError:
                continue
            seq = int(tail)
            self._seq = max(self._seq, seq + 1)
            old = self._disk.get(head)
            if old is None or old[1] < seq:
                if old is not None:
                    self._unlink_quiet(old[0])
                self._disk[head] = (path, seq, nbytes)
            else:
                self._unlink_quiet(path)

    def _unlink_quiet(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError as e:
            suppressed("kv_tier.unlink", e, self._log, self._rmetrics)

    def _refresh_gauges(self) -> None:
        if self._m is None:
            return
        with self._lock:
            dev = sum(1 for e in self._entries.values()
                      if e.tier == "device")
            host = sum(1 for e in self._entries.values()
                       if e.tier == "host")
            self._m.occupancy.labels(tier="device").set(dev)
            self._m.occupancy.labels(tier="host").set(host)
            self._m.occupancy.labels(tier="disk").set(len(self._disk))
            self._m.spill_bytes.set(
                sum(n for _, _, n in self._disk.values()))

    def _journal(self, name: str, **fields: object) -> None:
        if self._recorder is not None:
            self._recorder.record(name, **fields)

    # -- scheduler-thread API ----------------------------------------------

    def note_parked(self, session_id: str, slot: int,
                    now_s: float) -> None:
        """Bind *session_id* to its freshly parked device *slot*,
        superseding any older copy in any tier.  Scheduler thread."""
        try:
            with self._lock:
                old = self._entries.get(session_id)
                if old is not None and old.tier == "device" \
                        and old.slot != slot:
                    try:
                        self._engine.discard_session(old.slot)
                    except Exception as e:
                        suppressed("kv_tier.supersede", e, self._log,
                                   self._rmetrics)
                if old is not None and old.tier == "host":
                    self._host_bytes -= old.nbytes
                # a stale disk file (if any) stays: its rows are a
                # bit-exact PREFIX of the newer conversation, so a
                # crash before the next spill degrades to a partial
                # warm resume instead of serving nothing
                self._entries[session_id] = _Entry(
                    session_id, "device", slot=slot,
                    deadline=self._jittered(now_s, self.device_idle_s))
            self._refresh_gauges()
        except Exception as e:
            suppressed("kv_tier.note_parked", e, self._log,
                       self._rmetrics)

    def prepare(self, session_id: str, now_s: float,
                can_restore: bool = True) -> str:
        """Promote *session_id* to the device tier ahead of admission.
        Returns the tier that served the warm hit ("device" / "host" /
        "disk") or "" for a cold miss or any failure — the caller then
        simply omits the session from admission and the request
        re-prefills.  *can_restore* gates host/disk restores (they
        consume a slot the caller may need); a device hit needs no
        slot and always answers.  Scheduler thread; never raises."""
        tier = ""
        try:
            tier = self._prepare(session_id, now_s, can_restore)
        except Exception as e:
            suppressed("kv_tier.prepare", e, self._log, self._rmetrics)
        self._refresh_gauges()
        return tier

    def _prepare(self, session_id: str, now_s: float,
                 can_restore: bool) -> str:
        with self._lock:
            e = self._entries.get(session_id)
        if e is not None and e.tier == "device":
            if faults.ACTIVE is not None:
                try:
                    faults.ACTIVE.fire("kv.promote")
                except faults.InjectedFault as exc:
                    self._degraded("device", exc)
                    return ""
            with self._lock:
                e.deadline = self._jittered(now_s, self.device_idle_s)
            self._hit("device")
            return "device"
        if not can_restore:
            return ""
        if e is not None and e.tier == "host":
            return self._promote_host(e, now_s)
        h = sid_hash(session_id)
        with self._lock:
            on_disk = self._disk.get(h)
        if on_disk is not None:
            return self._promote_disk(session_id, h, on_disk, now_s)
        return ""

    def _degraded(self, tier: str, exc: BaseException) -> None:
        self._log.warning("kv_tier: %s promotion degraded to "
                          "re-prefill: %s", tier, exc)
        if self._m is not None:
            self._m.promotions.labels(tier=tier,
                                      outcome="degraded").inc()
        self._journal("tpu_kv_promote", tier=tier, outcome="degraded",
                      error=str(exc))

    def _hit(self, tier: str) -> None:
        with self._lock:
            self._hit_counts[tier] += 1
        if self._m is not None:
            self._m.hits.labels(tier=tier).inc()

    def _promote_host(self, e: _Entry, now_s: float) -> str:
        t0 = time.monotonic()
        try:
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("kv.promote")
            slot = int(self._engine.resume_session(e.state))
        # tpulint: disable=R2 -- not a swallow: _degraded() logs, journals tpu_kv_promote{outcome="degraded"} and counts the metric; the session stays parked in host RAM and this request re-prefills (acceptance: a tiering failure never fails the request)
        except Exception as exc:
            self._degraded("host", exc)
            return ""
        with self._lock:
            self._host_bytes -= e.nbytes
            self._entries[e.sid] = _Entry(
                e.sid, "device", slot=slot,
                deadline=self._jittered(now_s, self.device_idle_s))
        self._promoted("host", time.monotonic() - t0)
        return "host"

    def _promote_disk(self, sid: str, h: str,
                      rec: Tuple[str, int, int], now_s: float) -> str:
        path = rec[0]
        t0 = time.monotonic()
        try:
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("kv.promote")
            state = self._read_state(path)
            if state.get("session_id") != sid:
                # hash-prefix collision or foreign file: never resume
                # another conversation's KV
                raise MigrateError(
                    f"spill file {path} does not hold session")
            slot = int(self._engine.resume_session(state))
        except (MigrateError, ValueError) as exc:
            # corrupt / truncated / foreign: quarantine the file so the
            # store never retries a poisoned checkpoint
            with self._lock:
                if self._disk.get(h, (None,))[0] == path:
                    del self._disk[h]
            self._unlink_quiet(path)
            self._evicted("corrupt")
            self._degraded("disk", exc)
            return ""
        # tpulint: disable=R2 -- not a swallow: _degraded() logs, journals tpu_kv_promote{outcome="degraded"} and counts the metric; the spill file stays on disk for a later visit while this request re-prefills
        except Exception as exc:
            self._degraded("disk", exc)
            return ""
        with self._lock:
            if self._disk.get(h, (None,))[0] == path:
                del self._disk[h]
            self._entries[sid] = _Entry(
                sid, "device", slot=slot,
                deadline=self._jittered(now_s, self.device_idle_s))
        self._unlink_quiet(path)
        self._promoted("disk", time.monotonic() - t0)
        return "disk"

    def _promoted(self, tier: str, dt_s: float) -> None:
        with self._lock:
            self._promote_count += 1
        self._hit(tier)
        if self._m is not None:
            self._m.promotions.labels(tier=tier, outcome="ok").inc()
            self._m.resume_seconds.labels(tier=tier).observe(dt_s)
        self._journal("tpu_kv_promote", tier=tier, outcome="ok",
                      seconds=dt_s)

    def _read_state(self, path: str) -> Dict[str, object]:
        """Disk-tier fetch: breaker-gated, retried, watchdogged — the
        one promotion step that can wedge on a sick disk."""
        if not self._breaker.allow():
            raise CircuitOpenError("kv.disk: circuit open")

        def fetch() -> Dict[str, object]:
            with open(path, "rb") as f:
                return load_payload(f.read())

        try:
            state = self._watchdog.call(
                lambda: self._retry.call(
                    fetch, op="kv.promote", retry_on=(OSError,),
                    metrics=self._rmetrics, recorder=self._recorder))
        except (MigrateError, ValueError):
            # a cleanly-read-but-invalid file is the file's fault, not
            # the disk's: don't open the breaker for it
            raise
        except Exception:
            self._breaker.record_failure()
            raise
        self._breaker.record_success()
        return state

    def tick(self, now_s: float, slot_pressure: bool = False) -> None:
        """Run the demotion policy: service handler-thread export
        requests, demote idle device sessions, spill idle host
        sessions, enforce the host-RAM cap and disk newest-K, and
        (under *slot_pressure*) free a slot for waiting admissions.
        Scheduler thread; never raises."""
        try:
            self._tick(now_s, slot_pressure)
        except Exception as e:
            suppressed("kv_tier.tick", e, self._log, self._rmetrics)
        self._refresh_gauges()

    def _tick(self, now_s: float, slot_pressure: bool) -> None:
        with self._lock:
            exports = list(self._exports)
            self._exports.clear()
            stale = list(self._stale_slots)
            self._stale_slots.clear()
        for slot in stale:
            try:
                self._engine.discard_session(slot)
            except Exception as e:
                suppressed("kv_tier.stale_slot", e, self._log,
                           self._rmetrics)
        for req in exports:
            self._service_export(req)
        with self._lock:
            device = sorted((e for e in self._entries.values()
                             if e.tier == "device"),
                            key=lambda e: e.deadline)
            hosts = sorted((e for e in self._entries.values()
                            if e.tier == "host"),
                           key=lambda e: e.deadline)
        for e in device:
            if e.deadline <= now_s:
                self._demote_to_host(e, now_s, reason="idle")
        if slot_pressure and not self._engine.free_slots():
            with self._lock:
                device = sorted((x for x in self._entries.values()
                                 if x.tier == "device"),
                                key=lambda x: x.deadline)
            if device:
                self._demote_to_host(device[0], now_s, reason="slots")
        for e in hosts:
            if e.deadline <= now_s and self._entries.get(e.sid) is e:
                self._spill_or_drop(e, now_s, reason="idle")
        self._enforce_host_cap(now_s)
        self._gc_disk()

    def _service_export(self, req: _ExportReq) -> None:
        with self._lock:
            e = self._entries.get(req.sid)
        try:
            if e is None:
                req.error = "unknown session"
            elif e.tier == "device":
                state = self._engine.demote_session(e.slot)
                req.payload = dump_payload(state)
                with self._lock:
                    self._entries.pop(req.sid, None)
            elif e.tier == "host":
                assert e.state is not None
                req.payload = dump_payload(e.state)
                with self._lock:
                    self._entries.pop(req.sid, None)
                    self._host_bytes -= e.nbytes
            else:
                req.error = f"unexpected tier {e.tier}"
        except Exception as exc:
            req.error = str(exc)
            suppressed("kv_tier.export", exc, self._log, self._rmetrics)
        req.done.set()

    def demote_for_pages(self, now_s: float) -> bool:
        """Page-pressure valve: demote the closest-to-idle device
        session to host, freeing its pages.  Returns True when a
        session was demoted (the caller retries its allocation).
        Scheduler thread; never raises."""
        try:
            with self._lock:
                device = sorted((e for e in self._entries.values()
                                 if e.tier == "device"),
                                key=lambda e: e.deadline)
            if not device:
                return False
            ok = self._demote_to_host(device[0], now_s, reason="pages")
            self._refresh_gauges()
            return ok
        except Exception as e:
            suppressed("kv_tier.pressure", e, self._log, self._rmetrics)
            return False

    def _demote_to_host(self, e: _Entry, now_s: float,
                        reason: str) -> bool:
        try:
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("kv.demote")
            state = self._engine.demote_session(e.slot)
        except Exception as exc:
            # the session stays device-parked; idle demotion will
            # retry on the next tick
            suppressed("kv_tier.demote", exc, self._log, self._rmetrics)
            return False
        nbytes = _state_nbytes(state)
        with self._lock:
            self._entries[e.sid] = _Entry(
                e.sid, "host", state=state, nbytes=nbytes,
                deadline=self._jittered(now_s, self.host_idle_s))
            self._host_bytes += nbytes
            self._demote_count += 1
        if self._m is not None:
            self._m.demotions.labels(tier="host", reason=reason).inc()
        self._journal("tpu_kv_demote", session=sid_hash(e.sid),
                      tier="host", reason=reason, bytes=nbytes)
        self._enforce_host_cap(now_s)
        return True

    def _enforce_host_cap(self, now_s: float) -> None:
        while True:
            with self._lock:
                if self._host_bytes <= self.host_cap_bytes:
                    return
                hosts = sorted((e for e in self._entries.values()
                                if e.tier == "host"),
                               key=lambda e: e.deadline)
            if not hosts:
                return
            self._spill_or_drop(hosts[0], now_s, reason="host_cap")

    def _spill_or_drop(self, e: _Entry, now_s: float,
                       reason: str) -> None:
        """host → disk, or host → gone when the disk tier is missing
        or sick (bounded RAM beats unbounded hope)."""
        if self._spill(e, reason):
            return
        with self._lock:
            if self._entries.get(e.sid) is e:
                del self._entries[e.sid]
                self._host_bytes -= e.nbytes
        self._evicted(reason)

    def _spill(self, e: _Entry, reason: str) -> bool:
        if self._dir is None:
            return False
        try:
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("kv.spill")
            if not self._breaker.allow():
                raise CircuitOpenError("kv.disk: circuit open")
            assert e.state is not None
            payload = dump_payload(e.state)
            with self._lock:
                seq = self._seq
                self._seq += 1
            h = sid_hash(e.sid)
            path = os.path.join(self._dir,
                                f"{h}-{seq:08d}{_SPILL_SUFFIX}")

            def write() -> None:
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)

            try:
                self._retry.call(write, op="kv.spill",
                                 retry_on=(OSError,),
                                 metrics=self._rmetrics,
                                 recorder=self._recorder)
            except Exception:
                self._breaker.record_failure()
                raise
            self._breaker.record_success()
        except Exception as exc:
            suppressed("kv_tier.spill", exc, self._log, self._rmetrics)
            return False
        with self._lock:
            old = self._disk.get(h)
            self._disk[h] = (path, seq, len(payload))
            if self._entries.get(e.sid) is e:
                del self._entries[e.sid]
                self._host_bytes -= e.nbytes
            self._demote_count += 1
        if old is not None:
            self._unlink_quiet(old[0])
        if self._m is not None:
            self._m.demotions.labels(tier="disk", reason=reason).inc()
        self._journal("tpu_kv_spill", session=h, reason=reason,
                      bytes=len(payload), path=path)
        self._gc_disk()
        return True

    def _gc_disk(self) -> None:
        with self._lock:
            if len(self._disk) <= self.disk_keep:
                return
            by_age = sorted(self._disk.items(), key=lambda kv: kv[1][1])
            drop = by_age[:len(self._disk) - self.disk_keep]
            for h, _ in drop:
                del self._disk[h]
        for _, (path, _, _) in drop:
            self._unlink_quiet(path)
            self._evicted("disk_cap")

    def _evicted(self, reason: str) -> None:
        with self._lock:
            self._evict_count += 1
        if self._m is not None:
            self._m.evictions.labels(reason=reason).inc()
        self._journal("tpu_kv_evict", reason=reason)

    def spill_all(self, now_s: float) -> None:
        """Drain: push every session down to the disk tier so a
        clean shutdown loses nothing.  Scheduler thread; never
        raises."""
        try:
            with self._lock:
                device = [e for e in self._entries.values()
                          if e.tier == "device"]
            for e in device:
                self._demote_to_host(e, now_s, reason="drain")
            with self._lock:
                hosts = [e for e in self._entries.values()
                         if e.tier == "host"]
            for e in hosts:
                self._spill_or_drop(e, now_s, reason="drain")
        except Exception as e:
            suppressed("kv_tier.drain", e, self._log, self._rmetrics)
        self._refresh_gauges()

    # -- handler-thread API (cross-replica moves) --------------------------

    def export_session(self, session_id: str,
                       timeout_s: float = 5.0) -> bytes:
        """Hand the session's checkpoint to another replica (single-
        owner move: the local copy is dropped).  Raises KeyError
        (unknown), TimeoutError (scheduler busy), or RuntimeError."""
        h = sid_hash(session_id)
        claimed: Optional[Tuple[str, int, int]] = None
        req: Optional[_ExportReq] = None
        with self._lock:
            e = self._entries.get(session_id)
            if e is None:
                claimed = self._disk.pop(h, None)
                if claimed is None:
                    raise KeyError(session_id)
            elif e.tier == "host":
                assert e.state is not None
                payload = dump_payload(e.state)
                self._entries.pop(session_id, None)
                self._host_bytes -= e.nbytes
                self._refresh_gauges()
                return payload
            else:
                req = _ExportReq(session_id)
                self._exports.append(req)
        if claimed is not None:
            # the index slot is claimed; read outside the lock (disk
            # I/O must not block the scheduler's tick)
            try:
                state = self._read_state(claimed[0])
                if state.get("session_id") != session_id:
                    raise KeyError(session_id)
            except BaseException:
                with self._lock:
                    self._disk.setdefault(h, claimed)
                raise
            payload = dump_payload(state)
            self._unlink_quiet(claimed[0])
            self._refresh_gauges()
            return payload
        assert req is not None
        if not req.done.wait(timeout_s):
            raise TimeoutError(
                f"session export {sid_hash(session_id)} timed out")
        if req.payload is None:
            raise RuntimeError(req.error or "export failed")
        self._refresh_gauges()
        return req.payload

    def import_payload(self, raw: bytes, now_s: float) -> str:
        """Accept a checkpoint from another replica into the host
        tier (promotion to device happens on the session's first
        request here).  Returns the session_id; raises MigrateError /
        ValueError on a bad payload."""
        state = load_payload(raw)
        if state.get("kind") != "session":
            raise MigrateError(
                f"not a session checkpoint: {state.get('kind')!r}")
        sid = state.get("session_id")
        if not isinstance(sid, str) or not sid:
            raise MigrateError("payload carries no session_id")
        nbytes = _state_nbytes(state)
        with self._lock:
            old = self._entries.get(sid)
            if old is not None and old.tier == "device":
                # engine ops are scheduler-thread only: queue the
                # superseded slot for discard at the next tick
                self._stale_slots.append(old.slot)
            if old is not None and old.tier == "host":
                self._host_bytes -= old.nbytes
            self._entries[sid] = _Entry(
                sid, "host", state=state, nbytes=nbytes,
                deadline=self._jittered(now_s, self.host_idle_s))
            self._host_bytes += nbytes
        self._journal("tpu_kv_import", session=sid_hash(sid),
                      bytes=nbytes)
        self._refresh_gauges()
        return sid

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """The fixed-schema /statz block."""
        with self._lock:
            dev = sum(1 for e in self._entries.values()
                      if e.tier == "device")
            host = sum(1 for e in self._entries.values()
                       if e.tier == "host")
            return {
                "device": dev,
                "host": host,
                "host_bytes": self._host_bytes,
                "disk": len(self._disk),
                "disk_bytes": sum(n for _, _, n in self._disk.values()),
                "hits": dict(self._hit_counts),
                "demotions": self._demote_count,
                "promotions": self._promote_count,
                "evictions": self._evict_count,
            }


def empty_tier_stats() -> Dict[str, object]:
    """The same /statz schema when tiering is off — the block is
    always present so fleet roll-ups and schema tests stay simple."""
    return {
        "device": 0, "host": 0, "host_bytes": 0, "disk": 0,
        "disk_bytes": 0, "hits": {t: 0 for t in TIERS},
        "demotions": 0, "promotions": 0, "evictions": 0,
    }
