"""Fused conv/s1(SAME) + maxpool3x3/s2(VALID): the "flash-conv".

Handles any odd conv window (AlexNet's pooled stages use 3x3 and 5x5).

Why: the AlexNet conv head is HBM-activation-bound (BASELINE.md's
segment ablation), and the single largest remaining traffic item after
pool-before-relu and the Pallas pool is the conv OUTPUT tensor itself —
written by the conv, read right back by the pool (2 full passes of a
[B, 56, 56, 64] bf16 tensor per forward).  This kernel computes the
conv and pools it IN VMEM, writing only the 4x-smaller pooled output
(plus the int8 argmax index the scatter backward needs).  The pre-pool
activation never exists in HBM.

Forward mapping (per grid step = one batch lane-block x one block of
pool rows): the stride-1 conv over C_in channels is one MXU matmul per
output pixel:

    conv[h, w]  =  K_flat[[F, W^2*C]]  @  patch[[W^2*C, B]]

where ``patch`` stacks the window's input tiles (C, B) along the
sublane dim — tap-packing turns the 48-deep contraction into a
432-deep one (3x3) or 1600-deep (5x5), which is what makes the matmul
MXU-worthy.  Tiles are
(C sublane, B lane): the native orientation of the batch-minor
(H, W, C, B) conv activation layout (see pool.py's layout note).  Each
needed conv row is computed ONCE per block (rolling rows, cast to the
activation dtype so pooling sees exactly what the unfused pipeline
pools), then 3x3/s2 windows are maxed in VMEM with pool.py's
first-match argmax-index rule.

Backward (custom VJP, no second hand kernel): scatter the pooled
gradient through the index with pool.py's scatter kernel to get the
conv-output gradient, then let ``jax.vjp`` of XLA's own conv produce
dx/dK.  The backward still materializes dconv once — fusing the
backward too is the recorded next step — but the forward saves both
passes of the pre-pool tensor and select_and_scatter is gone.

Like pool.py: strides/windows static, interpret mode off-TPU, and the
kernel sticks to constructs proven on Mosaic in this repo (static
slices, sublane concats, 2D dot_general with f32 accumulation; no
gathers, no value dynamic_update_slice, no i1 vector algebra).  The
one new construct is a clamped dynamic ROW index into the x block
(major, untiled dim) for the SAME-padding halo.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .pool import (
    _batch_tiling,
    _block_spec,
    _first_match_idx,
    _out_dim,
    _pool_bwd_impl,
    _to_hwcb,
)

try:  # TPU memory spaces; absent on some non-TPU installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

POOL_WINDOW = 3          # pool window (VALID)
POOL_STRIDE = 2


def _compiler_params(interpret):
    if pltpu is None or interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=100 * 1024 * 1024,
    )


def _fused_kernel(h: int, w: int, window: int, pool_rows: int,
                  x_ref, k_ref, y_ref, idx_ref):
    """One grid step: pool rows [pi * pool_rows, ...) for one 128-wide
    batch block.

    x block:   (h, c, w, B) — channel-before-width layout so the W^2
               shifted row slices concatenate along the CONTRACTION
               dim with no in-kernel transpose (same-block for every
               pi, so the pipeline keeps it resident per batch block).
    k block:   (F, window^2 * c) tap-packed flat kernel, resident.
    y/idx:     (pool_rows, F, ow, B) — F-major; the host transposes
               the small pooled outputs back to spatial-major.

    The whole conv row is ONE MXU matmul, [F, W^2*C] @ [W^2*C, w*B]:
    per-pixel dots would trace O(w * W^2) ops and feed the MXU
    N=128-wide; row-batching traces O(W^2) and feeds it N=w*128.
    Pooling then runs on the row values with pool.py's parity-plane
    trick (reshape + static slices — no strided value slices, which
    Mosaic lowers to unsupported gathers)."""
    pi = pl.program_id(1)
    kf = k_ref[...]                      # [F, window^2 * C]
    ow = _out_dim(w, POOL_WINDOW, POOL_STRIDE)
    pad = window // 2                    # SAME padding offset
    f32 = jnp.float32
    dtype = y_ref.dtype
    feat = kf.shape[0]
    bsz = x_ref.shape[3]
    wq = -(-w // POOL_STRIDE)            # parity-plane cols

    def x_row(r):
        """Input row r as [c, w, B]; out-of-range rows read as zeros
        (conv SAME padding).  r is traced (derives from program_id)."""
        rc = jnp.clip(r, 0, h - 1)
        valid = ((r >= 0) & (r <= h - 1)).astype(x_ref.dtype)
        return x_ref[rc] * valid

    def conv_row(hh):
        """Conv output row hh as [F, w, B] in the activation dtype
        (pooling must see exactly what the unfused conv would emit)."""
        parts = []
        for di in range(window):
            row = x_row(hh + di - pad)
            for dj in range(window):
                s = dj - pad             # column shift
                lead = max(0, -s)        # zeros before the valid span
                lo = max(0, s)
                span = w - abs(s)
                sl = row[:, lo:lo + span]
                parts.append(jnp.pad(
                    sl, ((0, 0), (lead, w - lead - span), (0, 0))))
        patch = jnp.concatenate(parts, axis=0)   # [W^2*C, w, B]
        patch = patch.reshape(patch.shape[0], w * bsz)
        acc = lax.dot_general(
            kf, patch, (((1,), (0,)), ((), ())),
            preferred_element_type=f32,
        )
        return acc.reshape(feat, w, bsz).astype(dtype)

    # rolling rows: the block's pool rows need conv rows
    # [2*p0, 2*p0 + 2*pool_rows], each computed ONCE (adjacent pool
    # windows share rows; recompute would cost 1.5x the conv FLOPs)
    p0 = pi * pool_rows
    rows = [conv_row(2 * p0 + k) for k in range(2 * pool_rows + 1)]

    def plane(v, dj):
        """Columns 2*pw + dj of row value v, for all pw: [F, ow, B].
        Parity reshape keeps every slice unit-stride."""
        vp = jnp.pad(
            v, ((0, 0), (0, wq * POOL_STRIDE - w), (0, 0)))
        vr = vp.reshape(feat, wq, POOL_STRIDE, bsz)
        off = dj // POOL_STRIDE
        return vr[:, off:off + ow, dj % POOL_STRIDE]

    for pr in range(pool_rows):
        cand = [plane(rows[2 * pr + di], dj)
                for di in range(POOL_WINDOW)
                for dj in range(POOL_WINDOW)]
        cf = [t.astype(f32) for t in cand]
        m = cf[0]
        for t in cf[1:]:
            m = jnp.maximum(m, t)
        idx = _first_match_idx(cf, m)   # pool.py's shared tie-break
        y_ref[pr] = m.astype(dtype)
        idx_ref[pr] = idx.astype(jnp.int8)


def _pick_pool_rows(oh: int) -> int:
    """Pool-row block: a small divisor of oh bounds the rolling-row
    VMEM working set ((2*rows+1) x w x [F, B] tiles) and the unrolled
    kernel size; 1 always divides."""
    for cand in (3, 2, 1):
        if oh % cand == 0:
            return cand
    return 1


def _fused_fwd_impl(x, kernel, interpret):
    """x (B, H, W, C) NHWC, kernel (3, 3, C, F) HWIO ->
    (pooled (B, OH, OW, F) NHWC, idx (OH, OW, F, Bt) pool-layout)."""
    b, h, w, c = x.shape
    window = kernel.shape[0]
    if kernel.shape[:3] != (window, window, c) or window % 2 != 1:
        raise ValueError(
            f"kernel {kernel.shape} must be odd-square x C={c}")
    feat = kernel.shape[-1]
    oh = _out_dim(h, POOL_WINDOW, POOL_STRIDE)
    ow = _out_dim(w, POOL_WINDOW, POOL_STRIDE)
    bpad, lanes = _batch_tiling(b, interpret)
    bt = b + bpad
    # (H, C, W, Bt): channel-before-width so the kernel's shifted row
    # slices stack along the contraction dim without a relayout (the
    # producer-side transpose is XLA's to fuse into its layout choice)
    xt = _to_hwcb(x, bpad).transpose(0, 2, 1, 3)
    # tap-packed kernel [F, window^2 * C]: tap-major (di, dj),
    # channel-minor — the same order the kernel concatenates patches
    kf = kernel.astype(x.dtype).transpose(3, 0, 1, 2).reshape(feat, -1)
    pool_rows = _pick_pool_rows(oh)
    grid = (bt // lanes, oh // pool_rows)
    y, idx = pl.pallas_call(
        functools.partial(_fused_kernel, h, w, window, pool_rows),
        grid=grid,
        in_specs=[
            _block_spec((h, c, w, lanes), lambda bi, pi: (0, 0, 0, bi)),
            _block_spec((feat, window * window * c),
                        lambda bi, pi: (0, 0)),
        ],
        out_specs=[
            _block_spec((pool_rows, feat, ow, lanes),
                        lambda bi, pi: (pi, 0, 0, bi)),
            _block_spec((pool_rows, feat, ow, lanes),
                        lambda bi, pi: (pi, 0, 0, bi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((oh, feat, ow, bt), x.dtype),
            jax.ShapeDtypeStruct((oh, feat, ow, bt), jnp.int8),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(xt, kf)
    # back to spatial-major: y to NHWC for the caller, idx to the
    # (OH, OW, F, Bt) layout pool.py's scatter backward expects —
    # both are 4x-pooled tensors, cheap XLA transposes
    y = y.transpose(3, 0, 2, 1)[:b]          # (B, OH, OW, F)
    return y, idx.transpose(0, 2, 1, 3)


def _resolve(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _conv_ref(x, kernel):
    """The unfused conv this kernel replaces (used for its VJP)."""
    return lax.conv_general_dilated(
        x, kernel.astype(x.dtype), window_strides=(1, 1),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def conv_pool(x, kernel, interpret: Optional[bool] = None):
    """Fused stride-1 SAME conv (odd window) + 3x3/s2 VALID max-pool
    over NHWC.  Equivalent to
    ``nn.max_pool(conv(x, kernel), (3, 3), (2, 2))`` with the pre-pool
    activation never materialized in HBM.  Gradient tie-break matches
    XLA's select_and_scatter (first window offset in row-major
    order)."""
    y, _ = _fused_fwd_impl(x, kernel, _resolve(interpret))
    return y


def _vjp_fwd(x, kernel, interpret):
    y, idx = _fused_fwd_impl(x, kernel, _resolve(interpret))
    return y, (x, kernel, idx)


def _vjp_bwd(interpret, res, dp):
    x, kernel, idx = res
    b, h, w, _ = x.shape
    feat = kernel.shape[-1]
    # pooled grad -> conv-output grad via the index scatter (pool.py's
    # backward kernel), then XLA's own conv VJP for dx/dK — the
    # forward's win was never the conv FLOPs, it was the traffic
    dconv = _pool_bwd_impl(
        idx, dp, (b, h, w, feat), POOL_WINDOW, POOL_STRIDE,
        _resolve(interpret))
    _, conv_vjp = jax.vjp(_conv_ref, x, kernel)
    dx, dk = conv_vjp(dconv)
    return dx, dk.astype(kernel.dtype)


conv_pool.defvjp(_vjp_fwd, _vjp_bwd)
