"""Plugin lifecycle manager (≈ internal/pkg/manager + kubevirt/dpm reimpl)."""

from .manager import PluginManager

__all__ = ["PluginManager"]
