"""Plugin lifecycle: serve per-resource gRPC sockets, register with the
kubelet, re-register on kubelet restart, pulse the health heartbeat.

Reimplements the load-bearing behavior of kubevirt/device-plugin-manager
(vendored in the reference at vendor/github.com/kubevirt/device-plugin-manager/
pkg/dpm/manager.go:41-137, plugin.go:51-162) plus the reference's own manager
wrapper (internal/pkg/manager/manager.go:31-104):

- one unix socket + gRPC server per resource, named ``google.com_<res>`` in
  the kubelet device-plugin dir
- Register RPC to kubelet.sock with 3x3s retries
- watch the kubelet socket: on re-create, re-serve every plugin's endpoint
  socket (a restarting kubelet wipes the device-plugin dir) and re-register;
  on remove, keep serving and wait for the socket to come back
- pulse thread driving UpdateHealth → ListAndWatch resends
- resource-list diffing: start/stop plugin servers as the advertised
  resource set changes

The reference watches with fsnotify; here a poll watcher is the portable
default and the native tpuprobe inotify shim is used when built.
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import threading
import time
from typing import Dict, List, Optional

import grpc

from tpu_k8s_device_plugin import obs, resilience
from tpu_k8s_device_plugin.allocator import BestEffortPolicy
from tpu_k8s_device_plugin.resilience import faults
from tpu_k8s_device_plugin.plugin import TpuDevicePlugin
from tpu_k8s_device_plugin.plugin.plugin import PluginMetrics
from tpu_k8s_device_plugin.proto import (
    deviceplugin_pb2 as pluginapi,
    deviceplugin_pb2_grpc as pluginapi_grpc,
)
from tpu_k8s_device_plugin.types import (
    DeviceImpl,
    DevicePluginContext,
    constants,
)

log = logging.getLogger(__name__)

# Register retry shape (consumed by the shared RetryPolicy below; kept
# as module constants so tests can shrink the delay)
_REGISTER_RETRIES = 3
_REGISTER_RETRY_DELAY_S = 3.0
# bounded stop(): how long to wait for the watch/pulse threads to exit
# before logging and moving on (they are daemons; a wedged probe must
# not block process shutdown forever)
_THREAD_JOIN_TIMEOUT_S = 5.0


class _ServedPlugin:
    """One resource's plugin server + socket (≈ dpm devicePlugin)."""

    def __init__(self, resource: str, plugin: TpuDevicePlugin, socket_path: str):
        self.resource = resource
        self.plugin = plugin
        self.socket_path = socket_path
        self.server: Optional[grpc.Server] = None

    def serve(self) -> None:
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)
        self.server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=8)
        )
        pluginapi_grpc.add_DevicePluginServicer_to_server(
            self.plugin, self.server
        )
        self.server.add_insecure_port(f"unix://{self.socket_path}")
        self.server.start()
        log.info("serving %s on %s", self.resource, self.socket_path)

    def restart_server(self) -> None:
        """Tear down and re-create the gRPC server + socket, keeping the
        plugin (and its DeviceImpl state) alive.  Needed after a kubelet
        restart: kubelet wipes the device-plugin dir on startup, unlinking
        our socket while the old server keeps listening on a dead inode."""
        if self.server is not None:
            self.server.stop(grace=0.5).wait()
            self.server = None
        self.serve()

    def shutdown(self) -> None:
        self.plugin.stop()
        if self.server is not None:
            self.server.stop(grace=1.0).wait()
            self.server = None
        if os.path.exists(self.socket_path):
            try:
                os.remove(self.socket_path)
            except OSError:
                pass


class PluginManager:
    """Drives the full plugin lifecycle for a DeviceImpl."""

    def __init__(
        self,
        device_impl: DeviceImpl,
        pulse_seconds: int = 0,
        kubelet_dir: str = constants.DEVICE_PLUGIN_PATH,
        resource_namespace: str = constants.RESOURCE_NAMESPACE,
        kubelet_watch_interval_s: float = 1.0,
        slice_client=None,
        registry: Optional[obs.Registry] = None,
        recorder: Optional[obs.FlightRecorder] = None,
    ):
        self.impl = device_impl
        self.pulse = pulse_seconds
        self.kubelet_dir = kubelet_dir
        # the node's ONE metrics registry: plugin latency histograms,
        # pulse rounds, slice metrics (when the CLI shares it), and the
        # debug endpoint's bridged status snapshot all render from here
        self.registry = registry if registry is not None else obs.Registry()
        # the node's ONE flight recorder: Allocate/ListAndWatch spans,
        # device demotions/recoveries, pulse rounds, and (when the CLI
        # shares it) slice membership transitions journal here; the
        # debug /debug/traces and /debug/events endpoints read it and
        # --flight-record-dir dumps it on exit/SIGTERM
        self.recorder = (recorder if recorder is not None
                         else obs.FlightRecorder(registry=self.registry))
        # shared resilience instrumentation: Register retries, the
        # probe breaker/watchdog (wired into the impl below), and the
        # suppressed-error counter all render from this registry
        self.resilience = resilience.ResilienceMetrics(self.registry)
        set_res = getattr(device_impl, "set_resilience", None)
        if callable(set_res):
            set_res(metrics=self.resilience, recorder=self.recorder)
        self._plugin_metrics = PluginMetrics(self.registry)
        self._m_pulse = self.registry.histogram(
            "tpu_plugin_pulse_round_seconds",
            "One pulse round: rediscovery + slice heartbeat + "
            "plugin beats.", buckets=obs.LATENCY_BUCKETS_S)
        # optional multi-host slice client: the pulse loop heartbeats it
        # BEFORE beating the plugins, so each ListAndWatch resend already
        # reflects this round's local probe and the peers' latest verdict
        self.slice_client = slice_client
        self.kubelet_socket = os.path.join(kubelet_dir, "kubelet.sock")
        self.namespace = resource_namespace
        self._watch_interval = kubelet_watch_interval_s
        self._plugins: Dict[str, _ServedPlugin] = {}
        # guards _plugins: mutated by update_resources()/stop() on caller
        # threads while the kubelet-watch thread iterates it to re-register
        self._plugins_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- public API ---------------------------------------------------------

    def run(self, block: bool = True) -> None:
        """Start serving and registering; optionally block until stop()."""
        self._sync_plugins(self.impl.get_resource_names())
        self._register_all()
        t = threading.Thread(
            target=self._kubelet_watch_loop, name="kubelet-watch", daemon=True
        )
        t.start()
        self._threads.append(t)
        if self.pulse > 0:
            t = threading.Thread(
                target=self._pulse_loop, name="pulse", daemon=True
            )
            t.start()
            self._threads.append(t)
        if block:
            try:
                while not self._stop.is_set():
                    time.sleep(0.5)
            except KeyboardInterrupt:
                pass
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        with self._plugins_lock:
            plugins = list(self._plugins.values())
            self._plugins.clear()
        for sp in plugins:
            sp.shutdown()
        # join the watch/pulse threads with a bound: a thread that
        # fails to exit is a wedged call we must not wait on forever,
        # but it must also not die silently (leaked threads across
        # restarts are how socket flaps become fd exhaustion)
        me = threading.current_thread()
        for t in self._threads:
            if t is me:
                continue
            t.join(timeout=_THREAD_JOIN_TIMEOUT_S)
            if t.is_alive():
                log.warning(
                    "thread %s did not exit within %.0fs of stop()",
                    t.name, _THREAD_JOIN_TIMEOUT_S)
        self._threads = [t for t in self._threads if t.is_alive()]

    def update_resources(self, resources: List[str]) -> None:
        """Diff the advertised resource set, starting/stopping plugin
        servers as needed (≈ dpm manager.go:96-137)."""
        self._sync_plugins(resources)
        self._register_all()

    def status_snapshot(self) -> Dict[str, dict]:
        """Per-resource serving state for the debug endpoint.  Health comes
        from each plugin's last ListAndWatch frame (no hardware probing on
        this path — request rate stays decoupled from probe rate), falling
        back to the precomputed enumerate list before any stream opened."""
        with self._plugins_lock:
            plugins = list(self._plugins.items())
        out: Dict[str, dict] = {}
        for resource, sp in plugins:
            plugin = sp.plugin
            devices = plugin.last_devices
            if devices is None:
                try:
                    devices = self.impl.enumerate(plugin.ctx)
                except Exception as e:
                    # surfaced to the /debug caller in the payload, and
                    # logged so the failure is greppable without one
                    log.debug("debug-status enumerate failed for %s: %s",
                              resource, e)
                    out[resource] = {"error": str(e)}
                    continue
            out[resource] = {
                "endpoint": sp.socket_path,
                "devices": {d.ID: d.health for d in devices},
                "healthy": sum(d.health == constants.HEALTHY for d in devices),
                "unhealthy": sum(d.health != constants.HEALTHY for d in devices),
                # capability, not failure: False covers both "allocator
                # init failed, degraded to kubelet default" AND "no
                # topology allocator by design" (VFIO passthrough) —
                # either way GetPreferredAllocation answers first-fit
                "preferred_allocation_enabled": (
                    not plugin.ctx.get_allocator_error()
                ),
                "rpc_counts": plugin.counters(),
            }
        return out

    # -- internals ----------------------------------------------------------

    def _endpoint(self, resource: str) -> str:
        return f"{self.namespace}_{resource}"

    def _sync_plugins(self, resources: List[str]) -> None:
        wanted = set(resources)
        with self._plugins_lock:
            current = set(self._plugins)
            removed = [self._plugins.pop(r) for r in current - wanted]
        for sp in removed:
            log.info("resource %s no longer advertised; stopping", sp.resource)
            sp.shutdown()
        for resource in sorted(wanted - current):
            if self._stop.is_set():
                return
            ctx = DevicePluginContext(resource, BestEffortPolicy())
            plugin = TpuDevicePlugin(self.impl, ctx,
                                     metrics=self._plugin_metrics,
                                     recorder=self.recorder)
            plugin.start()
            sp = _ServedPlugin(
                resource,
                plugin,
                os.path.join(self.kubelet_dir, self._endpoint(resource)),
            )
            sp.serve()
            with self._plugins_lock:
                if self._stop.is_set():
                    # a concurrent stop() already drained _plugins; inserting
                    # now would resurrect a server nothing will ever shut down
                    sp.shutdown()
                    return
                self._plugins[resource] = sp

    def _register_all(self) -> None:
        with self._plugins_lock:
            plugins = list(self._plugins.items())
        for resource, sp in plugins:
            self._register(resource, sp)

    def _register(self, resource: str, sp: _ServedPlugin) -> bool:
        """Register RPC through the shared RetryPolicy (≈ dpm
        plugin.go:127-162, which hardcoded 3x3s; the policy adds
        jittered exponential backoff, retry metrics, and stop-event
        abort).  A final failure is non-fatal: the kubelet-watch loop
        re-registers on the next socket event."""
        try:
            options = self.impl.get_options(sp.plugin.ctx)
        except Exception as e:
            log.error("GetOptions failed for %s: %s", resource, e)
            options = pluginapi.DevicePluginOptions()
        req = pluginapi.RegisterRequest(
            version=constants.KUBELET_DP_VERSION,
            endpoint=self._endpoint(resource),
            resource_name=f"{self.namespace}/{resource}",
            options=options,
        )

        def _rpc():
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("kubelet.register")
            with grpc.insecure_channel(
                f"unix://{self.kubelet_socket}"
            ) as ch:
                stub = pluginapi_grpc.RegistrationStub(ch)
                stub.Register(req, timeout=5.0)

        policy = resilience.RetryPolicy(
            max_attempts=_REGISTER_RETRIES,
            initial_backoff_s=_REGISTER_RETRY_DELAY_S,
            max_backoff_s=_REGISTER_RETRY_DELAY_S * 4,
        )
        try:
            policy.call(
                _rpc, op="kubelet.register",
                retry_on=(grpc.RpcError, faults.InjectedFault),
                stop=self._stop, metrics=self.resilience,
                recorder=self.recorder, logger=log)
        except (grpc.RpcError, faults.InjectedFault) as e:
            log.warning("register %s failed after retries: %s",
                        resource, e)
            return False
        except resilience.CircuitOpenError:
            return False  # stop() landed before the first attempt
        log.info("registered %s/%s with kubelet", self.namespace, resource)
        return True

    def _kubelet_watch_loop(self) -> None:
        """Re-register on kubelet socket re-creation; stop plugin servers
        while the socket is gone (≈ dpm manager.go:73-84).  Uses the native
        inotify shim when available, else stat polling."""
        def make_watcher():
            try:
                from tpu_k8s_device_plugin.hostinfo import tpuprobe
                return tpuprobe.DirWatcher(self.kubelet_dir)
            except Exception as e:
                # no native shim / no inotify budget: poll instead —
                # counted, not silent
                resilience.suppressed("manager.make_watcher", e,
                                      logger=log,
                                      metrics=self.resilience)
                return None

        watcher = make_watcher()
        last_stat = self._socket_stat()
        while not self._stop.is_set():
            if watcher is not None:
                try:
                    watcher.wait(timeout_s=self._watch_interval)
                except OSError as e:
                    # ESTALE: the watched dir was deleted+recreated (some
                    # kubelet restarts do this) — re-watch the new inode;
                    # only fall back to polling when that fails too
                    log.warning("inotify watch broke (%s); re-creating", e)
                    try:
                        watcher.close()
                    except Exception as ce:
                        resilience.suppressed("manager.watcher_close",
                                              ce, logger=log,
                                              metrics=self.resilience)
                    watcher = make_watcher()
                    if watcher is None:
                        log.warning("watch re-creation failed; polling")
            else:
                time.sleep(self._watch_interval)
            cur = self._socket_stat()
            if cur == last_stat:
                continue
            if cur is None:
                log.warning("kubelet socket disappeared; waiting for restart")
            else:
                log.info(
                    "kubelet socket (re)created; re-serving and "
                    "re-registering plugins"
                )
                # small grace: kubelet needs a moment to start serving
                time.sleep(1.0)
                if self._stop.is_set():
                    return
                # snapshot after the sleep, and re-serve under the lock so a
                # concurrent stop()/_sync_plugins shutdown can't be undone by
                # resurrecting a server the manager no longer tracks
                with self._plugins_lock:
                    for sp in self._plugins.values():
                        # kubelet wipes the dp dir on restart; our endpoint
                        # socket must exist before Register advertises it
                        if not os.path.exists(sp.socket_path):
                            sp.restart_server()
                self._register_all()
            last_stat = cur

    def _socket_stat(self):
        try:
            st = os.stat(self.kubelet_socket)
            # ctime matters: a fast kubelet restart can reuse the inode
            # (observed on tmpfs), making (ino, dev) alone miss the re-create
            return (st.st_ino, st.st_dev, st.st_ctime_ns)
        except OSError:
            return None

    def _pulse_loop(self) -> None:
        """Heartbeat: re-check the hardware inventory, then trigger a
        health refresh on every plugin (≈ manager.go:39-46).  The beat
        after a rediscovery is what pushes the changed device list down
        every open ListAndWatch stream."""
        while not self._stop.wait(self.pulse):
            # every pulse round is a ROOT trace: the slice heartbeat it
            # drives carries the same trace-id over gRPC, so one id
            # links a local probe to the coordinator's verdict
            ctx = obs.new_trace()
            with self._plugins_lock:
                resources = sorted(self._plugins)
            with obs.span("tpu_plugin_pulse_round",
                          histogram=self._m_pulse, logger=log,
                          trace=ctx, recorder=self.recorder) as sp:
                sp.annotate(resources=",".join(resources) or "-")
                self._maybe_rediscover()
                if self.slice_client is not None:
                    # heartbeat first: ships the fresh local probe to the
                    # coordinator and pulls the slice verdict this round's
                    # update_health frames will render (one wedged chip
                    # anywhere reaches every member within one
                    # pulse+heartbeat)
                    try:
                        self.slice_client.heartbeat_now(
                            trace=ctx.child())
                    except Exception as e:
                        log.warning("slice heartbeat failed: %s", e)
                with self._plugins_lock:
                    plugins = list(self._plugins.values())
                for sp in plugins:
                    sp.plugin.beat()

    def _maybe_rediscover(self) -> None:
        """Runtime resource rediscovery (≈ dpm ResUpdateChan consumption,
        vendor/.../dpm/manager.go:96-137): when the chip set or partition
        modes changed, re-diff the served resources and re-init surviving
        plugins' allocators against the new device set."""
        if self._stop.is_set():
            return
        try:
            changed = self.impl.rediscover()
        except Exception as e:
            log.error("rediscovery probe failed: %s", e)
            return
        if not changed:
            return
        resources = self.impl.get_resource_names()
        log.info("re-advertising resources after hardware change: %s",
                 resources)
        with self._plugins_lock:
            survivors = set(self._plugins)
        self.update_resources(resources)
        # Fresh plugins were init'd against the new device set inside
        # _sync_plugins; only survivors hold a stale allocator.
        with self._plugins_lock:
            stale = [sp for r, sp in self._plugins.items() if r in survivors]
        for sp in stale:
            self._reinit_allocator(sp)

    def _reinit_allocator(self, sp: _ServedPlugin) -> None:
        """Swap in a freshly initialised policy.  A new context + policy is
        built off to the side and published with one reference assignment:
        in-flight GetPreferredAllocation calls keep the fully-built old
        policy; later calls see the fully-built new one.  Mutating the live
        policy in place would let a concurrent RPC observe a half-built
        weight table."""
        ctx = DevicePluginContext(sp.resource, BestEffortPolicy())
        try:
            self.impl.start(ctx)
        except Exception as e:
            log.error("allocator re-init failed for %s: %s", sp.resource, e)
            ctx.set_allocator_error(True)
        sp.plugin.ctx = ctx
