"""Flight recorder: a bounded in-memory event journal with crash dumps.

Histograms answer "how slow is the p99"; the flight recorder answers
"what happened to THIS request" and — crucially — "what happened in the
last minute before the process died".  It is the black-box layer the
production accelerator stacks in PAPERS.md pair with their metrics:

- a fixed-memory ring of :class:`Event` records (drop-oldest on
  overflow, with a dropped-events counter on the owning registry so the
  loss is itself observable),
- every ``Span.end`` plus discrete lifecycle events (device
  demotion/recovery, membership transitions, 429 sheds, slow-client
  drops, grammar-cap rejections) land here, each stamped with trace-id,
  monotonic + wall time, and key=value attrs,
- read paths: ``events()`` / ``trace()`` snapshots feed the
  ``/debug/events`` and ``/debug/traces`` endpoints,
- crash safety: ``install_dump_handlers()`` wires atexit + a chaining
  SIGTERM handler (and a faulthandler file for hard crashes) that write
  the journal as JSON-lines to ``--flight-record-dir``, so a post-mortem
  survives the process that produced it.

Thread-safe, stdlib only, and deliberately cheap: one lock hop and one
deque append per event — safe to call from the serving scheduler loop.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import threading
import time
from collections import deque
from types import FrameType
from typing import (
    TYPE_CHECKING,
    Deque,
    Dict,
    Iterable,
    List,
    Optional,
)

if TYPE_CHECKING:  # typing only: no runtime import-order coupling
    from .core import Counter, Registry
    from .trace import TraceContext

log = logging.getLogger(__name__)

DEFAULT_CAPACITY = 4096

# crash dumps accumulate across restarts (the file name embeds pid +
# time precisely so restarts never clobber them); keep the newest K
# and garbage-collect the rest at dump time so a crash-looping pod
# cannot fill the node's disk with post-mortems
DEFAULT_DUMP_KEEP = 20


class Event:
    """One journal entry (see module docstring)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t_wall",
                 "t_mono", "attrs")

    def __init__(self, name: str, trace_id: str = "", span_id: str = "",
                 parent_id: str = "",
                 attrs: Optional[Dict[str, object]] = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_wall = time.time()
        self.t_mono = time.monotonic()
        self.attrs: Dict[str, object] = attrs if attrs is not None else {}

    def to_dict(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            # the cross-PROCESS link: a hop continues the caller's
            # traceparent as a child context, so parent_id points at
            # the upstream process's span and obs.stitch can re-link
            # events from several journals into one tree
            "parent_id": self.parent_id,
            "t_wall": self.t_wall,
            "t_mono": self.t_mono,
            "attrs": self.attrs,
        }


def _jsonable(v: object) -> object:
    """Attrs must survive json.dumps in a signal-time dump; anything
    exotic degrades to its str() at RECORD time, not dump time."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class FlightRecorder:
    """Thread-safe bounded ring journal (see module docstring)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: Optional["Registry"] = None,
                 dump_keep: int = DEFAULT_DUMP_KEEP) -> None:
        if capacity < 1:
            raise ValueError("recorder capacity must be >= 1")
        if dump_keep < 1:
            raise ValueError("dump_keep must be >= 1")
        self.capacity = capacity
        self.dump_keep = dump_keep
        self._lock = threading.Lock()
        self._ring: Deque[Event] = deque(maxlen=capacity)
        self._recorded = 0
        self._dropped = 0
        self._dump_gc = 0
        # loss is observable: the registry (when the owning surface has
        # one) carries the totals next to the latency histograms the
        # events annotate
        self._m_events: Optional["Counter"] = None
        self._m_dropped: Optional["Counter"] = None
        self._m_dump_gc: Optional["Counter"] = None
        if registry is not None:
            self._m_events = registry.counter(
                "tpu_flight_events_total",
                "Events recorded into the flight-recorder ring.")
            self._m_dropped = registry.counter(
                "tpu_flight_dropped_events_total",
                "Events evicted from the full flight-recorder ring "
                "(drop-oldest).")
            self._m_dump_gc = registry.counter(
                "tpu_flight_dump_gc_total",
                "Old flight-record dump files deleted to keep the "
                "newest dump_keep in --flight-record-dir.")
        self._dump_paths: List[str] = []
        self._dump_installed = False

    # -- write path ---------------------------------------------------------

    def record(self, name: str, trace: Optional["TraceContext"] = None,
               trace_id: str = "", span_id: str = "",
               parent_id: str = "", **attrs: object) -> None:
        """Append one event.  *trace* (a TraceContext) wins over the
        explicit id strings (its parent link rides along, so a
        cross-process stitcher can re-link hops); attrs are sanitized
        to JSON scalars now so a SIGTERM-time dump can never fail on a
        live object."""
        if trace is not None:
            trace_id = trace.trace_id
            span_id = trace.span_id
            parent_id = trace.parent_id or ""
        ev = Event(name, trace_id=trace_id, span_id=span_id,
                   parent_id=parent_id,
                   attrs={k: _jsonable(v) for k, v in attrs.items()})
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
                if self._m_dropped is not None:
                    self._m_dropped.inc()
            self._ring.append(ev)  # deque(maxlen) evicts the oldest
            self._recorded += 1
        if self._m_events is not None:
            self._m_events.inc()

    # -- read paths ---------------------------------------------------------

    @property
    def recorded(self) -> int:
        with self._lock:
            return self._recorded

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def events(self, since: float = 0.0, trace_id: Optional[str] = None,
               name: Optional[str] = None,
               limit: int = 1000) -> List[Dict[str, object]]:
        """Snapshot of matching events, oldest first.  *since* filters
        on wall time (the /debug/events?since= contract), *trace_id*
        on the stamped trace, *name* on the event name."""
        with self._lock:
            snap = list(self._ring)
        out: List[Dict[str, object]] = []
        for ev in snap:
            if ev.t_wall <= since:
                continue
            if trace_id is not None and ev.trace_id != trace_id:
                continue
            if name is not None and ev.name != name:
                continue
            out.append(ev.to_dict())
        return out[-limit:] if limit else out

    def trace_ids(self, limit: int = 64) -> List[Dict[str, object]]:
        """The most recent distinct trace ids with event counts —
        the /debug/traces index view."""
        with self._lock:
            snap = list(self._ring)
        counts: Dict[str, int] = {}
        last: Dict[str, float] = {}
        for ev in snap:
            if not ev.trace_id:
                continue
            counts[ev.trace_id] = counts.get(ev.trace_id, 0) + 1
            last[ev.trace_id] = ev.t_wall
        order = sorted(counts, key=lambda t: last[t], reverse=True)
        return [{"trace_id": t, "events": counts[t], "last_t_wall": last[t]}
                for t in order[:limit]]

    # -- crash dumps --------------------------------------------------------

    def dump(self, path: str) -> int:
        """Write the journal as JSON-lines to *path* (one event per
        line, oldest first).  Returns the event count written."""
        with self._lock:
            snap = list(self._ring)
            dropped = self._dropped
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            # header line first: a dump that was truncated mid-write is
            # still identifiable, and the drop count frames what's missing
            f.write(json.dumps({
                "flight_record": True, "pid": os.getpid(),
                "events": len(snap), "dropped": dropped,
                "t_wall": time.time(),
            }) + "\n")
            for ev in snap:
                f.write(json.dumps(ev.to_dict()) + "\n")
        os.replace(tmp, path)  # a crash mid-dump never leaves half a file
        return len(snap)

    def dump_to_dir(self, dir_path: str) -> Optional[str]:
        """One dump file in *dir_path*, named by pid + wall time so
        restarts never clobber the post-mortem they should explain.
        After a successful dump, older dumps past ``dump_keep`` are
        deleted (newest-first by mtime) so crash loops cannot grow the
        directory without bound; deletions count in
        ``tpu_flight_dump_gc_total``."""
        try:
            os.makedirs(dir_path, exist_ok=True)
            path = os.path.join(
                dir_path,
                f"flight-{os.getpid()}-{int(time.time())}.jsonl")
            self.dump(path)
        except OSError as e:
            log.error("flight-record dump to %s failed: %s", dir_path, e)
            return None
        self._gc_dumps(dir_path)
        return path

    @property
    def dump_gc_count(self) -> int:
        with self._lock:
            return self._dump_gc

    def _gc_dumps(self, dir_path: str) -> None:
        """Keep the newest ``dump_keep`` flight-*.jsonl dumps in
        *dir_path*.  Best-effort: a GC failure must never fail the
        dump that just succeeded (this runs on SIGTERM/atexit)."""
        try:
            dumps = [
                os.path.join(dir_path, f)
                for f in os.listdir(dir_path)
                if f.startswith("flight-") and f.endswith(".jsonl")
            ]
            dumps.sort(key=lambda p: (os.path.getmtime(p), p),
                       reverse=True)
            stale = dumps[self.dump_keep:]
        except OSError as e:
            log.warning("flight-record dump GC scan failed: %s", e)
            return
        removed = 0
        for p in stale:
            try:
                os.remove(p)
                removed += 1
            except OSError as e:
                log.warning("flight-record dump GC of %s failed: %s",
                            p, e)
        if removed:
            with self._lock:
                self._dump_gc += removed
            if self._m_dump_gc is not None:
                self._m_dump_gc.inc(removed)
            log.info("flight-record dump GC removed %d old dump(s) "
                     "from %s (keep %d)", removed, dir_path,
                     self.dump_keep)

    def install_dump_handlers(self, dir_path: str,
                              signals: Iterable[int] = (signal.SIGTERM,)
                              ) -> None:
        """Dump the journal on process exit: atexit (clean exits and
        sys.exit paths), a CHAINING handler on each listed signal
        (k8s sends SIGTERM on pod shutdown), and a faulthandler file in
        *dir_path* for hard crashes the interpreter can still report.
        Idempotent; signal installation is skipped off the main thread
        (library embedders) — atexit still covers them."""
        if self._dump_installed:
            return
        self._dump_installed = True

        def _dump_once(_done: List[bool] = [False]) -> None:
            if _done[0]:
                return
            _done[0] = True
            path = self.dump_to_dir(dir_path)
            if path:
                log.info("flight record dumped to %s", path)

        atexit.register(_dump_once)
        try:
            import faulthandler
            os.makedirs(dir_path, exist_ok=True)
            f = open(os.path.join(
                dir_path, f"faulthandler-{os.getpid()}.log"), "w")
            faulthandler.enable(file=f)
        except (OSError, RuntimeError) as e:
            log.warning("faulthandler file unavailable: %s", e)
        for sig in signals:
            try:
                prev = signal.getsignal(sig)

                def _handler(signum: int, frame: Optional[FrameType],
                             _prev: object = prev) -> None:
                    _dump_once()
                    if callable(_prev):
                        _prev(signum, frame)
                    elif _prev != signal.SIG_IGN:
                        # default disposition: terminate with the
                        # conventional 128+sig status
                        raise SystemExit(128 + signum)

                signal.signal(sig, _handler)
            except (ValueError, OSError) as e:
                # not the main thread, or an unsupported signal: the
                # atexit hook still covers orderly shutdown
                log.warning("cannot install dump handler for %s: %s",
                            sig, e)
