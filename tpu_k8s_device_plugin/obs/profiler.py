"""Always-on sampling profiler: the flight data recorder for CPU time.

PR 12's ``/debug/profile`` answers "what is the process doing RIGHT
NOW" — an operator asks, jax.profiler captures, the operator reads the
dump.  It can never answer the incident question: what was the process
doing in the seconds *before* the page fired?  By the time a human
asks, the evidence is gone.

:class:`SamplingProfiler` closes that gap dependency-free: a daemon
thread walks :func:`sys._current_frames` at a configurable rate
(default 19 hz — deliberately prime, so the sampler can't phase-lock
with a 10/20/100 hz periodic workload and systematically over- or
under-count it), folds each thread's stack into the flamegraph
``frame;frame;leaf`` form, and accumulates (stack, phase) → count
buckets in a bounded per-second ring.  Every sample is tagged with the
scheduler's current window phase (``dispatch``/``harvest``/``stream``/
``idle``) and the number of in-flight requests, so a profile slice
reads as "during dispatch, under load, the process was HERE".

Bounds are structural, not aspirational: the ring holds at most
``window_s`` one-second buckets, distinct folded stacks are interned up
to ``max_stacks`` (overflow folds into the ``(other)`` leaf), and every
bucket key is drawn from that bounded set — memory is flat no matter
how long the process runs (the determinism suite drives +1000 ticks
and asserts exactly that).  Measured overhead is exported as
``tpu_profiler_overhead_ratio`` and tested to stay under 3% wall time
at the default rate.

Composition with jax.profiler (PR 12): a jax capture and the sampler
must not double-account — while a capture runs the server wraps it in
:meth:`SamplingProfiler.suspend`, which parks the sampling thread
(ticks are still counted as ``suspended`` so the timeline shows the
gap honestly) instead of sampling the capture machinery itself.

Exposed on every HTTP surface as::

    GET /debug/pprof?seconds=N&format=folded   # flamegraph.pl-ready
    GET /debug/pprof?seconds=N&format=json     # tpu-profile/v1 schema

The folded output prepends the phase as a synthetic root frame
(``phase:dispatch;module.func;...``) so a flamegraph splits by phase
with zero post-processing.

Stdlib only.  Test seams: ``frames_fn``/``now_fn`` inject fake frame
maps and clocks, and :meth:`sample_once` runs one sampling pass inline
— the determinism suite never needs a real thread or a real sleep.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    Iterator,
    List,
    Mapping,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from .core import Counter, Gauge, Registry

# schema tag for the JSON form — bundles and obs_query key on it
PROFILE_SCHEMA = "tpu-profile/v1"

DEFAULT_HZ = 19.0
DEFAULT_WINDOW_S = 600.0
DEFAULT_MAX_STACKS = 512

# frames deeper than this fold into a "(deep)" marker: a runaway
# recursion must cost bounded bytes per sample, like everything else
MAX_FRAMES = 64

# the interning overflow leaf: once max_stacks distinct stacks have
# been seen, new shapes aggregate here instead of growing the set
OVERFLOW_STACK = "(other)"

# phase tag used when no phase_fn is wired (router, exporter, plugin)
NO_PHASE = "none"

# BucketKey/Bucket: per-second accumulation cell.  The value list is
# [sample_count, active_request_sum] — mean active load per stack is
# recovered at read time as sum/count.
_BucketKey = Tuple[str, str]
_Bucket = Tuple[int, Dict[_BucketKey, List[float]]]


def fold_stack(frame: Any, limit: int = MAX_FRAMES) -> str:
    """Fold one thread's frame chain into ``root;...;leaf`` form.

    Frames render as ``module.function`` (the flamegraph convention);
    the chain is walked leaf→root via ``f_back`` then reversed, and
    chains deeper than *limit* keep the leaf-most frames under a
    ``(deep)`` root so pathological recursion stays bounded.
    """
    names: List[str] = []
    depth = 0
    while frame is not None:
        code = frame.f_code
        mod = frame.f_globals.get("__name__", "?")
        names.append(f"{mod}.{code.co_name}")
        frame = frame.f_back
        depth += 1
        if depth >= limit:
            names.append("(deep)")
            break
    names.reverse()
    return ";".join(names)


class SamplingProfiler:
    """Background stack sampler with a bounded phase-tagged ring.

    Parameters
    ----------
    registry:
        Optional :class:`Registry` for the profiler's own (bounded)
        meta-metrics.  No per-stack labels ever reach the registry —
        stacks live only in the ring (the O1 cardinality contract).
    hz:
        Sampling rate.  19 by default (prime — see module docstring).
    window_s:
        Ring span in seconds; one bucket per second.
    max_stacks:
        Interning cap on distinct folded stacks.
    phase_fn:
        Zero-arg callable returning the current scheduler phase string
        (``IterationScheduler.phase``); samples tag ``none`` without it.
    active_fn:
        Zero-arg callable returning the current in-flight request
        count; each sample accumulates it so slices report mean load.
    frames_fn / now_fn:
        Test seams; default to :func:`sys._current_frames` and
        :func:`time.time`.
    """

    def __init__(self, registry: Optional[Registry] = None, *,
                 hz: float = DEFAULT_HZ,
                 window_s: float = DEFAULT_WINDOW_S,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 phase_fn: Optional[Callable[[], str]] = None,
                 active_fn: Optional[Callable[[], int]] = None,
                 frames_fn: Optional[
                     Callable[[], Mapping[int, Any]]] = None,
                 now_fn: Optional[Callable[[], float]] = None) -> None:
        if hz <= 0:
            raise ValueError("hz must be positive")
        if window_s < 1:
            raise ValueError("window_s must be >= 1")
        if max_stacks < 1:
            raise ValueError("max_stacks must be >= 1")
        self.hz = float(hz)
        self.window_s = float(window_s)
        self.max_stacks = int(max_stacks)
        self._phase_fn = phase_fn
        self._active_fn = active_fn
        self._frames_fn = frames_fn or sys._current_frames
        self._now = now_fn or time.time

        self._lock = threading.Lock()
        # ring: maxlen bounds memory structurally (one bucket a second)
        self._buckets: Deque[_Bucket] = deque(
            maxlen=max(1, int(self.window_s)))
        self._known: Set[str] = set()
        self._suspended = 0
        self._ticks = 0
        self._samples = 0
        self._suspended_ticks = 0
        self._busy_s = 0.0
        self._started_mono: Optional[float] = None
        self._first_t: Optional[float] = None
        self._last_t: Optional[float] = None

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the sampling thread's own ident (set when it starts): its
        # stack is excluded from samples.  Inline sample_once() calls
        # (tests) run on a caller thread and are NOT excluded.
        self._self_ident: Optional[int] = None

        self._c_ticks: Optional[Counter] = None
        self._c_samples: Optional[Counter] = None
        self._c_suspended: Optional[Counter] = None
        self._g_stacks: Optional[Gauge] = None
        self._g_overhead: Optional[Gauge] = None
        if registry is not None:
            self._c_ticks = registry.counter(
                "tpu_profiler_ticks_total",
                "Sampling passes attempted by the continuous profiler "
                "(includes suspended passes).")
            self._c_samples = registry.counter(
                "tpu_profiler_samples_total",
                "Thread stack samples folded into the profile ring.")
            self._c_suspended = registry.counter(
                "tpu_profiler_suspended_ticks_total",
                "Sampling passes skipped while the profiler was "
                "suspended (e.g. during a jax.profiler capture).")
            self._g_stacks = registry.gauge(
                "tpu_profiler_stacks",
                "Distinct folded stacks currently interned by the "
                "continuous profiler (bounded by its max_stacks cap).")
            self._g_overhead = registry.gauge(
                "tpu_profiler_overhead_ratio",
                "Measured fraction of wall time the continuous "
                "profiler's sampling thread spends on-CPU.")
            registry.on_collect(self._collect)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Start the sampling thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return
            if self._started_mono is None:
                self._started_mono = time.perf_counter()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="tpu-profiler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        """Stop the sampling thread (idempotent, joins briefly)."""
        with self._lock:
            t = self._thread
            self._thread = None
        if t is None:
            return
        self._stop.set()
        t.join(timeout=2.0)

    def _run(self) -> None:
        self._self_ident = threading.get_ident()
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample_once()
            # tpulint: disable=R2 -- the profiler must NEVER take down or log-spam the process it observes at 19hz; a broken pass loses one tick and the tick counter still shows the gap
            except Exception:
                pass

    @contextmanager
    def suspend(self, reason: str = "jax_profiler") -> Iterator[None]:
        """Park sampling for the duration of the block (re-entrant).

        Used around jax.profiler captures so the two profilers compose:
        suspended passes are counted (the timeline shows the gap) but
        record no stacks — no double-accounting of capture machinery.
        """
        del reason  # documented intent; the counter is the record
        with self._lock:
            self._suspended += 1
        try:
            yield
        finally:
            with self._lock:
                self._suspended -= 1

    @property
    def suspended(self) -> bool:
        with self._lock:
            return self._suspended > 0

    # -- sampling -----------------------------------------------------------

    def sample_once(self) -> int:
        """Run one sampling pass; returns stacks recorded (0 when
        suspended).  Public so tests drive passes deterministically."""
        t0 = time.perf_counter()
        now = float(self._now())
        with self._lock:
            if self._started_mono is None:
                self._started_mono = t0
            self._ticks += 1
            if self._c_ticks is not None:
                self._c_ticks.inc()
            if self._suspended > 0:
                self._suspended_ticks += 1
                if self._c_suspended is not None:
                    self._c_suspended.inc()
                self._busy_s += time.perf_counter() - t0
                return 0
        phase = NO_PHASE
        if self._phase_fn is not None:
            try:
                phase = str(self._phase_fn() or NO_PHASE)
            # tpulint: disable=R2 -- a broken phase probe degrades one sample's TAG to 'none'; raising or logging at sample rate would make the profiler the incident
            except Exception:
                phase = NO_PHASE
        active = 0
        if self._active_fn is not None:
            try:
                active = int(self._active_fn())
            # tpulint: disable=R2 -- same contract as the phase probe: a broken load probe zeroes one sample's annotation, never the sampling pass
            except Exception:
                active = 0
        # fold outside the lock: frame objects are read-only snapshots
        folded: List[str] = []
        frames = self._frames_fn()
        for ident, frame in list(frames.items()):
            if ident == self._self_ident:
                continue  # never profile the profiler
            try:
                folded.append(fold_stack(frame))
            # tpulint: disable=R2 -- frames are snapshots of live threads and can mutate mid-walk; losing one thread's sample this tick is the only safe degradation
            except Exception:
                continue
        n = 0
        with self._lock:
            sec = int(now)
            if not self._buckets or self._buckets[-1][0] != sec:
                self._buckets.append((sec, {}))
            bucket = self._buckets[-1][1]
            for stack in folded:
                if stack not in self._known:
                    if len(self._known) < self.max_stacks:
                        self._known.add(stack)
                    else:
                        stack = OVERFLOW_STACK
                cell = bucket.get((stack, phase))
                if cell is None:
                    cell = [0.0, 0.0]
                    bucket[(stack, phase)] = cell
                cell[0] += 1.0
                cell[1] += float(active)
                n += 1
            self._samples += n
            if self._c_samples is not None and n:
                self._c_samples.inc(n)
            if self._first_t is None:
                self._first_t = now
            self._last_t = now
            self._busy_s += time.perf_counter() - t0
        return n

    # -- reading ------------------------------------------------------------

    def overhead_ratio(self) -> float:
        """Fraction of wall time spent inside sampling passes since the
        first pass — the measured (not estimated) profiler cost."""
        with self._lock:
            if self._started_mono is None:
                return 0.0
            wall = time.perf_counter() - self._started_mono
            if wall <= 0:
                return 0.0
            return self._busy_s / wall

    def stack_count(self) -> int:
        with self._lock:
            return len(self._known)

    def _collect(self) -> None:
        if self._g_stacks is not None:
            self._g_stacks.set(float(self.stack_count()))
        if self._g_overhead is not None:
            self._g_overhead.set(self.overhead_ratio())

    def _slice(self, seconds: Optional[float]
               ) -> Tuple[Dict[_BucketKey, List[float]],
                          List[Tuple[int, float]]]:
        """Aggregate the last *seconds* of ring buckets (None = whole
        window) into one {(stack, phase): [count, active_sum]} map plus
        a per-second sample-count timeline."""
        now = float(self._now())
        cutoff = (-1.0 if seconds is None
                  else now - max(0.0, float(seconds)))
        agg: Dict[_BucketKey, List[float]] = {}
        timeline: List[Tuple[int, float]] = []
        with self._lock:
            for sec, bucket in self._buckets:
                if sec < cutoff:
                    continue
                total = 0.0
                for key, (count, active_sum) in bucket.items():
                    cell = agg.get(key)
                    if cell is None:
                        cell = [0.0, 0.0]
                        agg[key] = cell
                    cell[0] += count
                    cell[1] += active_sum
                    total += count
                timeline.append((sec, total))
        return agg, timeline

    def folded(self, seconds: Optional[float] = None) -> str:
        """The flamegraph.pl/speedscope form: one ``stack count`` line
        per (stack, phase), phase prepended as a synthetic root frame
        so a flamegraph splits by phase for free."""
        agg, _ = self._slice(seconds)
        lines = []
        for (stack, phase), (count, _active) in sorted(agg.items()):
            root = f"phase:{phase or NO_PHASE}"
            body = f"{root};{stack}" if stack else root
            lines.append(f"{body} {int(count)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def as_json(self, seconds: Optional[float] = None
                ) -> Dict[str, Any]:
        """The ``tpu-profile/v1`` document incident bundles embed."""
        agg, timeline = self._slice(seconds)
        stacks = []
        for (stack, phase), (count, active_sum) in sorted(
                agg.items(), key=lambda kv: -kv[1][0]):
            stacks.append({
                "stack": stack,
                "phase": phase,
                "count": int(count),
                "mean_active": (active_sum / count) if count else 0.0,
            })
        with self._lock:
            doc: Dict[str, Any] = {
                "schema": PROFILE_SCHEMA,
                "hz": self.hz,
                "window_s": self.window_s,
                "seconds": (float(seconds)
                            if seconds is not None else None),
                "ticks": self._ticks,
                "samples": self._samples,
                "suspended_ticks": self._suspended_ticks,
                "first_t": self._first_t,
                "last_t": self._last_t,
            }
        doc["overhead_ratio"] = self.overhead_ratio()
        doc["stacks"] = stacks
        doc["timeline"] = [[sec, n] for sec, n in timeline]
        return doc

    def handle_pprof(self, params: Mapping[str, Sequence[str]]
                     ) -> Tuple[str, str]:
        """The shared ``GET /debug/pprof`` implementation: parse
        ``seconds``/``format`` query params, return (content_type,
        body).  Raises ValueError on malformed input — surfaces map
        that to a 400, exactly like ``/debug/query``."""
        import json as _json

        raw_seconds = params.get("seconds", [])
        seconds: Optional[float] = None
        if raw_seconds:
            seconds = float(raw_seconds[0])
            if not 0 < seconds <= self.window_s:
                raise ValueError(
                    f"seconds must be in (0, {self.window_s:g}]")
        fmt = (params.get("format", ["folded"]) or ["folded"])[0]
        if fmt == "folded":
            return ("text/plain; charset=utf-8", self.folded(seconds))
        if fmt == "json":
            return ("application/json",
                    _json.dumps(self.as_json(seconds), indent=2,
                                sort_keys=True) + "\n")
        raise ValueError("format must be 'folded' or 'json'")
