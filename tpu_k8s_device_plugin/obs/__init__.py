"""Unified observability core: one registry, one renderer, spans,
trace contexts, and the flight recorder.

Every Prometheus surface in this repo (plugin debug endpoint, health
exporter, serving server, slice metrics) renders through
:class:`Registry`; request-scoped tracing rides :class:`TraceContext`
(W3C ``traceparent``) through :class:`Span` log lines, OpenMetrics
exemplars, and :class:`FlightRecorder` events.  See :mod:`.core` /
:mod:`.trace` / :mod:`.recorder` for design notes and
``docs/user-guide/observability.md`` for the full reference.
"""

from .alerts import (
    ALERT_TRANSITION_EVENT,
    SEVERITY_INFO,
    SEVERITY_PAGE,
    SEVERITY_TICKET,
    AlertCondition,
    AlertEvaluator,
    AlertRule,
    burn_rate,
    burn_rate_rules,
    load_alert_rules,
    parse_alert_rules,
    threshold_rule,
)
from .core import (
    FAST_BUCKETS_S,
    LATENCY_BUCKETS_S,
    OPENMETRICS_CONTENT_TYPE,
    SLOW_BUCKETS_S,
    TEXT_CONTENT_TYPE,
    Counter,
    Gauge,
    Histogram,
    ProcessCollector,
    Registry,
    ScrapeMeta,
    attach_process_collector,
    escape_help,
    escape_label_value,
    histogram_quantile,
    negotiate_openmetrics,
    parse_exposition,
)
from .incident import (
    BUNDLE_PREFIX,
    BUNDLE_SCHEMA,
    INCIDENT_EVENT,
    TSDB_SNAPSHOT_SCHEMA,
    IncidentManager,
    read_bundle,
)
from .profiler import (
    PROFILE_SCHEMA,
    SamplingProfiler,
    fold_stack,
)
from .recorder import Event, FlightRecorder
from .slo import (
    SLOAccountant,
    SLOPolicy,
    default_slo_policies,
    parse_slo_specs,
)
from .span import Span, span
from .stitch import event_severity, flatten, render_tree, stitch
from .trace import (
    TraceContext,
    new_trace,
    parse_traceparent,
    trace_from_header,
)
from .tsdb import (
    TSDB,
    expr_metric_names,
    format_duration,
    parse_duration,
    parse_expr,
)

__all__ = [
    "ALERT_TRANSITION_EVENT",
    "BUNDLE_PREFIX",
    "BUNDLE_SCHEMA",
    "FAST_BUCKETS_S",
    "INCIDENT_EVENT",
    "LATENCY_BUCKETS_S",
    "OPENMETRICS_CONTENT_TYPE",
    "PROFILE_SCHEMA",
    "SEVERITY_INFO",
    "SEVERITY_PAGE",
    "SEVERITY_TICKET",
    "SLOW_BUCKETS_S",
    "TEXT_CONTENT_TYPE",
    "TSDB",
    "TSDB_SNAPSHOT_SCHEMA",
    "AlertCondition",
    "AlertEvaluator",
    "AlertRule",
    "Counter",
    "Event",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "IncidentManager",
    "ProcessCollector",
    "Registry",
    "SLOAccountant",
    "SLOPolicy",
    "SamplingProfiler",
    "ScrapeMeta",
    "Span",
    "TraceContext",
    "attach_process_collector",
    "burn_rate",
    "burn_rate_rules",
    "default_slo_policies",
    "escape_help",
    "escape_label_value",
    "event_severity",
    "expr_metric_names",
    "flatten",
    "fold_stack",
    "format_duration",
    "histogram_quantile",
    "load_alert_rules",
    "negotiate_openmetrics",
    "new_trace",
    "parse_alert_rules",
    "parse_duration",
    "parse_expr",
    "parse_exposition",
    "parse_slo_specs",
    "parse_traceparent",
    "read_bundle",
    "render_tree",
    "span",
    "stitch",
    "threshold_rule",
    "trace_from_header",
]
