"""Unified observability core: one registry, one renderer, spans,
trace contexts, and the flight recorder.

Every Prometheus surface in this repo (plugin debug endpoint, health
exporter, serving server, slice metrics) renders through
:class:`Registry`; request-scoped tracing rides :class:`TraceContext`
(W3C ``traceparent``) through :class:`Span` log lines, OpenMetrics
exemplars, and :class:`FlightRecorder` events.  See :mod:`.core` /
:mod:`.trace` / :mod:`.recorder` for design notes and
``docs/user-guide/observability.md`` for the full reference.
"""

from .core import (
    FAST_BUCKETS_S,
    LATENCY_BUCKETS_S,
    OPENMETRICS_CONTENT_TYPE,
    SLOW_BUCKETS_S,
    TEXT_CONTENT_TYPE,
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_help,
    escape_label_value,
    histogram_quantile,
    negotiate_openmetrics,
    parse_exposition,
)
from .recorder import Event, FlightRecorder
from .slo import (
    SLOAccountant,
    SLOPolicy,
    default_slo_policies,
    parse_slo_specs,
)
from .span import Span, span
from .stitch import flatten, render_tree, stitch
from .trace import (
    TraceContext,
    new_trace,
    parse_traceparent,
    trace_from_header,
)

__all__ = [
    "FAST_BUCKETS_S",
    "LATENCY_BUCKETS_S",
    "OPENMETRICS_CONTENT_TYPE",
    "SLOW_BUCKETS_S",
    "TEXT_CONTENT_TYPE",
    "Counter",
    "Event",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Registry",
    "SLOAccountant",
    "SLOPolicy",
    "Span",
    "TraceContext",
    "default_slo_policies",
    "escape_help",
    "escape_label_value",
    "flatten",
    "histogram_quantile",
    "negotiate_openmetrics",
    "new_trace",
    "parse_exposition",
    "parse_slo_specs",
    "parse_traceparent",
    "render_tree",
    "span",
    "stitch",
    "trace_from_header",
]
