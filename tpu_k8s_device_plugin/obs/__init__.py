"""Unified metrics core: one registry, one renderer, spans.

Every Prometheus surface in this repo (plugin debug endpoint, health
exporter, serving server, slice metrics) renders through
:class:`Registry`; see :mod:`.core` for the design notes and
``docs/user-guide/observability.md`` for the full series reference.
"""

from .core import (
    FAST_BUCKETS_S,
    LATENCY_BUCKETS_S,
    SLOW_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_help,
    escape_label_value,
    histogram_quantile,
    parse_exposition,
)
from .span import Span, span

__all__ = [
    "FAST_BUCKETS_S",
    "LATENCY_BUCKETS_S",
    "SLOW_BUCKETS_S",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "Span",
    "escape_help",
    "escape_label_value",
    "histogram_quantile",
    "parse_exposition",
    "span",
]
