"""Alert-triggered incident bundles: the automatic post-mortem.

PR 18 closed the *detection* loop — burn-rate pages fire from the
in-process TSDB.  Diagnosis was still manual: an on-call had to race
the flight-recorder ring and hand-stitch ``/debug/query``,
``/debug/traces``, ``/alerts`` and ``/statz`` before the evidence aged
out of the bounded rings.  :class:`IncidentManager` closes that half:
it subscribes to the :class:`~.alerts.AlertEvaluator` state machine
and, the moment a page-severity rule transitions to firing, writes one
self-contained directory under ``--incident-dir``::

    incident-<alert>-<epoch>/
        alert.json       evaluator status + the ring's transition log
        journal.jsonl    full flight-recorder dump (header + events)
        tsdb.json        snapshot of the rule's referenced families
                         plus the tpu_serve_*/tpu_router_* core set
        profile.folded   last-N-seconds continuous profile (flamegraph)
        profile.json     same slice, tpu-profile/v1 schema
        <collector>.json surface snapshots (statz, slowest SLO-missed
                         traces, ...) — whatever the surface wired in
        replicas/...     router only: per-replica bundle fragments
        meta.json        written LAST: schema tag + file manifest

    The bundle is built under a hidden ``.incident-tmp-*`` name and
    renamed into place, so a reader listing ``incident-*`` never sees
    a partial bundle (meta.json doubling as the completeness marker).

Operational guardrails, all tested:

- **rate limit** — one bundle per alert per ``min_interval_s`` (a
  flapping page must not write the disk full),
- **GC** — newest ``keep`` bundles survive, foreign files are spared
  (same contract as the flight recorder's dump GC),
- **isolation** — the evaluator hook only enqueues; a dedicated worker
  thread does the writing, and every collector is individually
  guarded, so a hung ``/statz`` fetch or a SIGKILLed replica degrades
  one file to an error marker instead of wedging alert evaluation
  (chaos episode 16 proves this with a real kill),
- **accounting** — ``tpu_incident_bundles_total{alert}``,
  ``tpu_incident_bundle_seconds`` and a ``tpu_incident_bundle``
  journal event.

``tools/obs_query.py --incident DIR`` renders a bundle offline.
Stdlib only, like the rest of :mod:`~tpu_k8s_device_plugin.obs`.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Tuple,
)

from .alerts import (
    ALERT_TRANSITION_EVENT,
    AlertEvaluator,
    AlertRule,
    SEVERITY_PAGE,
    STATE_FIRING,
)
from .core import LATENCY_BUCKETS_S, Registry
from .profiler import SamplingProfiler
from .recorder import FlightRecorder
from .tsdb import TSDB, Selector, expr_metric_names

log = logging.getLogger(__name__)

# schema tags (obs_query --incident keys on these)
BUNDLE_SCHEMA = "tpu-incident/v1"
TSDB_SNAPSHOT_SCHEMA = "tpu-incident-tsdb/v1"

# journal event written after every successful bundle
INCIDENT_EVENT = "tpu_incident_bundle"

# bundle directory naming: the GC and obs_query both match this prefix
BUNDLE_PREFIX = "incident-"
_TMP_PREFIX = ".incident-tmp-"

DEFAULT_KEEP = 8
DEFAULT_MIN_INTERVAL_S = 300.0
DEFAULT_PROFILE_WINDOW_S = 60.0
DEFAULT_METRIC_PREFIXES = ("tpu_serve_", "tpu_router_")

# collector return value: anything json.dumps can take (default=str
# backstops the rest) — or a ready string for non-JSON payloads
Collector = Callable[[], Any]
ExtraFilesFn = Callable[[], Mapping[str, Any]]


def _write_json(path: str, doc: Any) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=str)
        f.write("\n")


class IncidentManager:
    """Subscribe to an evaluator; write bundles when pages fire.

    Parameters
    ----------
    dir_path:
        The ``--incident-dir``.  Created if missing.
    evaluator:
        The surface's :class:`AlertEvaluator`; the manager registers a
        transition hook on it at construction.
    registry / recorder / tsdb / profiler:
        The surface's observability stack; each optional piece that is
        wired in contributes its file to the bundle.
    collectors:
        ``{filename: zero-arg callable}`` surface snapshots (e.g.
        ``{"statz.json": server.statz}``).  Filenames ending ``.json``
        serialize the return value; others are written verbatim (str).
    extra_files_fn:
        Called once per bundle for dynamic multi-file payloads —
        returns ``{relative/path: content}``.  The router uses this to
        pull per-replica fragments into ``replicas/<id>/``.
    keep / min_interval_s / profile_window_s / metric_prefixes /
    severities:
        Guardrails; see module docstring.
    now_fn:
        Test seam for the wall clock.
    """

    def __init__(self, dir_path: str, evaluator: AlertEvaluator, *,
                 registry: Registry,
                 recorder: Optional[FlightRecorder] = None,
                 tsdb: Optional[TSDB] = None,
                 profiler: Optional[SamplingProfiler] = None,
                 collectors: Optional[Mapping[str, Collector]] = None,
                 extra_files_fn: Optional[ExtraFilesFn] = None,
                 keep: int = DEFAULT_KEEP,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
                 profile_window_s: float = DEFAULT_PROFILE_WINDOW_S,
                 metric_prefixes: Iterable[str] =
                 DEFAULT_METRIC_PREFIXES,
                 severities: Iterable[str] = (SEVERITY_PAGE,),
                 now_fn: Optional[Callable[[], float]] = None) -> None:
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.dir_path = dir_path
        self._evaluator = evaluator
        self._recorder = recorder
        self._tsdb = tsdb
        self._profiler = profiler
        self._collectors: Dict[str, Collector] = dict(collectors or {})
        self._extra_files_fn = extra_files_fn
        self.keep = int(keep)
        self.min_interval_s = float(min_interval_s)
        self.profile_window_s = float(profile_window_s)
        self._metric_prefixes = tuple(metric_prefixes)
        self._severities = frozenset(severities)
        self._now = now_fn or time.time

        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.Lock()
        self._last_bundle: Dict[str, float] = {}
        # the hook only ENQUEUES — writing happens on the worker so a
        # slow disk or hung collector can never stall rule evaluation
        self._queue: "queue.Queue[Optional[Tuple[AlertRule, float, Optional[float]]]]" \
            = queue.Queue(maxsize=4)
        self._worker: Optional[threading.Thread] = None

        self._c_bundles = registry.counter(
            "tpu_incident_bundles_total",
            "Incident bundles written, by the alert whose firing "
            "transition triggered them.",
            ("alert",))
        self._h_seconds = registry.histogram(
            "tpu_incident_bundle_seconds",
            "Wall time spent assembling one incident bundle.",
            buckets=LATENCY_BUCKETS_S)
        # boot-materialize the per-alert children for every rule this
        # manager can trigger on: the schema is stable from scrape 1
        for rule in evaluator.rules:
            if rule.severity in self._severities:
                self._c_bundles.labels(alert=rule.name)

        evaluator.add_transition_hook(self._on_transition)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Start the bundle-writer thread (idempotent)."""
        with self._lock:
            if self._worker is not None:
                return
            self._worker = threading.Thread(
                target=self._run, name="tpu-incident", daemon=True)
            self._worker.start()

    def stop(self) -> None:
        """Stop the writer (idempotent; drains nothing — pending
        triggers are dropped, the journal already has the alert)."""
        with self._lock:
            t = self._worker
            self._worker = None
        if t is None:
            return
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        t.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            rule, at, value = item
            try:
                self.write_bundle(rule, at, value)
            except Exception:
                log.exception("incident bundle for %s failed",
                              rule.name)

    # -- trigger path -------------------------------------------------------

    def _on_transition(self, rule: AlertRule, state_from: str,
                       state_to: str, at: float,
                       value: Optional[float]) -> None:
        """The evaluator hook: filter, rate-limit, enqueue."""
        if state_to != STATE_FIRING:
            return
        if rule.severity not in self._severities:
            return
        now = float(self._now())
        with self._lock:
            last = self._last_bundle.get(rule.name)
            if last is not None and now - last < self.min_interval_s:
                log.info("incident bundle for %s suppressed "
                         "(rate limit: %gs since last)",
                         rule.name, now - last)
                return
            self._last_bundle[rule.name] = now
        try:
            self._queue.put_nowait((rule, at, value))
        except queue.Full:
            # journal still has the transition; losing the bundle is
            # the correct degradation under a trigger storm
            log.warning("incident bundle queue full; dropping "
                        "trigger for %s", rule.name)

    # -- bundle assembly ----------------------------------------------------

    def write_bundle(self, rule: AlertRule, at: float,
                     value: Optional[float]) -> str:
        """Assemble one bundle synchronously; returns its final path.

        Public so tests (and the smoke tool) can drive a bundle
        without going through the evaluator.  Atomic: everything is
        written under a hidden tmp name in the same directory, then
        renamed into place in one step.
        """
        t0 = time.perf_counter()
        now = float(self._now())
        stamp = int(now * 1000)
        final = os.path.join(self.dir_path,
                             f"{BUNDLE_PREFIX}{rule.name}-{stamp}")
        tmp = os.path.join(self.dir_path,
                           f"{_TMP_PREFIX}{rule.name}-{stamp}")
        os.makedirs(tmp)
        files: List[str] = []
        errors: Dict[str, str] = {}

        def _guarded(relpath: str,
                     write: Callable[[str], None]) -> None:
            path = os.path.join(tmp, relpath)
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                write(path)
                files.append(relpath)
            except Exception as e:  # one bad file, not a lost bundle
                errors[relpath] = f"{type(e).__name__}: {e}"
                log.exception("incident bundle %s: %s failed",
                              rule.name, relpath)

        _guarded("alert.json",
                 lambda p: _write_json(p, self._alert_doc(now)))
        if self._recorder is not None:
            _guarded("journal.jsonl",
                     lambda p: self._recorder.dump(p)
                     if self._recorder is not None else None)
        if self._tsdb is not None:
            _guarded("tsdb.json",
                     lambda p: _write_json(
                         p, self._tsdb_doc(rule, now)))
        if self._profiler is not None:
            prof = self._profiler
            win = self.profile_window_s
            _guarded("profile.folded",
                     lambda p: self._write_text(p, prof.folded(win)))
            _guarded("profile.json",
                     lambda p: _write_json(p, prof.as_json(win)))
        for relpath, fn in sorted(self._collectors.items()):
            _guarded(relpath, lambda p, fn=fn: self._write_payload(
                p, fn()))
        if self._extra_files_fn is not None:
            try:
                extra = dict(self._extra_files_fn())
            except Exception as e:
                extra = {}
                errors["<extra_files>"] = f"{type(e).__name__}: {e}"
                log.exception("incident bundle %s: extra files failed",
                              rule.name)
            for relpath, content in sorted(extra.items()):
                _guarded(relpath,
                         lambda p, c=content: self._write_payload(p, c))

        # meta.json LAST: its presence certifies a complete bundle
        meta: Dict[str, Any] = {
            "schema": BUNDLE_SCHEMA,
            "alert": rule.name,
            "severity": rule.severity,
            "description": rule.description,
            "state_to": STATE_FIRING,
            "at": at,
            "value": value,
            "created_t": now,
            "pid": os.getpid(),
            "files": sorted(files),
            "errors": errors,
        }
        _write_json(os.path.join(tmp, "meta.json"), meta)
        os.rename(tmp, final)

        dt = time.perf_counter() - t0
        self._c_bundles.labels(alert=rule.name).inc()
        self._h_seconds.observe(dt)
        if self._recorder is not None:
            self._recorder.record(
                INCIDENT_EVENT, alert=rule.name,
                severity=rule.severity, dir=final, files=len(files),
                errors=len(errors), duration_s=dt)
        self._gc()
        return final

    @staticmethod
    def _write_text(path: str, text: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)

    @staticmethod
    def _write_payload(path: str, content: Any) -> None:
        if isinstance(content, str) and not path.endswith(".json"):
            IncidentManager._write_text(path, content)
        else:
            _write_json(path, content)

    def _alert_doc(self, now: float) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "status": self._evaluator.status(now),
            "transitions": [],
        }
        if self._recorder is not None:
            doc["transitions"] = self._recorder.events(
                name=ALERT_TRANSITION_EVENT)
        return doc

    def _tsdb_doc(self, rule: AlertRule, now: float) -> Dict[str, Any]:
        """Snapshot the rule's referenced families plus every retained
        family matching the core prefixes — the bundle must stand
        alone, so over-collecting beats a missing series."""
        assert self._tsdb is not None
        names = set()
        for cond in rule.conditions:
            try:
                names.update(expr_metric_names(cond.expr))
            except ValueError:
                pass
        for name in self._tsdb.series_names():
            if name.startswith(self._metric_prefixes):
                names.add(name)
            # histogram rules reference the base name; retained series
            # carry _bucket/_sum/_count — keep the whole family
            elif any(name.startswith(n) for n in list(names)):
                names.add(name)
        series: List[Dict[str, Any]] = []
        for name in sorted(names):
            for labels, points in self._tsdb.points(
                    Selector(name, ()), 0.0, now):
                series.append({
                    "name": name,
                    "labels": labels,
                    "points": [[t, v] for t, v in points],
                })
        return {
            "schema": TSDB_SNAPSHOT_SCHEMA,
            "at": now,
            "alert": rule.name,
            "series": series,
        }

    # -- GC -----------------------------------------------------------------

    def _gc(self) -> None:
        """Keep the newest ``keep`` bundles; spare everything that is
        not an ``incident-*`` directory (same contract as the flight
        recorder's dump GC — an operator's notes survive)."""
        try:
            entries = []
            for name in os.listdir(self.dir_path):
                if not name.startswith(BUNDLE_PREFIX):
                    continue
                path = os.path.join(self.dir_path, name)
                if not os.path.isdir(path):
                    continue
                try:
                    entries.append((os.path.getmtime(path), path))
                except OSError:
                    continue
            entries.sort(reverse=True)
            for _, path in entries[self.keep:]:
                self._rmtree(path)
        except OSError:
            log.exception("incident bundle GC failed")

    @staticmethod
    def _rmtree(path: str) -> None:
        """Best-effort recursive removal (shutil-free by taste, and a
        failure must never propagate into the worker loop)."""
        for root, dirs, names in os.walk(path, topdown=False):
            for n in names:
                try:
                    os.unlink(os.path.join(root, n))
                except OSError:
                    pass
            for d in dirs:
                try:
                    os.rmdir(os.path.join(root, d))
                except OSError:
                    pass
        try:
            os.rmdir(path)
        except OSError:
            pass


def read_bundle(dir_path: str) -> Dict[str, Any]:
    """Load a bundle directory back into one dict keyed by relative
    file path, ``meta`` parsed and validated first — the offline half
    (``obs_query --incident``) and the schema round-trip test both go
    through here."""
    meta_path = os.path.join(dir_path, "meta.json")
    if not os.path.isfile(meta_path):
        raise ValueError(
            f"{dir_path}: not an incident bundle (no meta.json)")
    with open(meta_path, "r", encoding="utf-8") as f:
        meta = json.load(f)
    if meta.get("schema") != BUNDLE_SCHEMA:
        raise ValueError(
            f"{dir_path}: unknown bundle schema "
            f"{meta.get('schema')!r}")
    out: Dict[str, Any] = {"meta": meta}
    for rel in meta.get("files", []):
        path = os.path.join(dir_path, rel)
        try:
            if rel.endswith(".json"):
                with open(path, "r", encoding="utf-8") as f:
                    out[rel] = json.load(f)
            else:
                with open(path, "r", encoding="utf-8") as f:
                    out[rel] = f.read()
        except (OSError, ValueError) as e:
            out[rel] = {"error": f"{type(e).__name__}: {e}"}
    return out
