"""Trace context: W3C ``traceparent`` parse/format + child derivation.

PR 3 gave every surface latency histograms and per-operation spans, but
each span was an island: the serving request, the Allocate that placed
its pod, and the heartbeat that demoted its devices could not be
stitched together.  A :class:`TraceContext` is the thread-light thread
between them — a (trace-id, span-id, parent) triple that rides HTTP
headers (``traceparent``), gRPC metadata, span log lines, histogram
exemplars, and flight-recorder events, so ONE id greps across every
surface a request touched.

Wire format is the W3C Trace Context ``traceparent`` header::

    00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>

Malformed or absent headers fall back to a fresh root trace (the W3C
"restart the trace" rule): propagation is best-effort and can never
reject a request.  Stdlib only, like the rest of ``obs``.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Optional

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

# all-zero ids are invalid per the W3C spec (they mean "no trace")
_ZERO_TRACE = "0" * 32
_ZERO_SPAN = "0" * 16


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclass(frozen=True)
class TraceContext:
    """One position in a trace: this operation's span-id inside the
    request-wide trace-id, plus the parent span that caused it."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    sampled: bool = True

    def child(self) -> "TraceContext":
        """A child context: same trace, fresh span-id, this span as
        parent — what a sub-operation (queue wait, admit, one stream
        write) carries so its log line links back here."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_rand_hex(8),
            parent_id=self.span_id,
            sampled=self.sampled,
        )

    def to_traceparent(self) -> str:
        """The W3C ``traceparent`` header value for this context."""
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")


def new_trace() -> TraceContext:
    """A fresh root context (new trace-id, no parent)."""
    return TraceContext(trace_id=_rand_hex(16), span_id=_rand_hex(8))


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``traceparent`` header value; None when malformed.

    Per the spec: exactly four ``-``-separated lowercase-hex fields,
    version ``ff`` and all-zero trace/span ids are invalid.  The caller
    decides the fallback (usually :func:`new_trace`)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip())
    if not m:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == _ZERO_TRACE or span_id == _ZERO_SPAN:
        return None
    return TraceContext(trace_id=trace_id, span_id=span_id,
                        sampled=bool(int(flags, 16) & 0x01))


def trace_from_header(value: Optional[str]) -> TraceContext:
    """The front-door rule in one call: continue the caller's trace as
    a CHILD context when the header parses, else start a new root.  A
    malformed header degrades to a fresh trace, never an error."""
    parsed = parse_traceparent(value)
    if parsed is None:
        return new_trace()
    return parsed.child()
