"""Cross-process trace stitching: flight-recorder events -> span tree.

One trace-id crosses processes (client -> router -> replica ->
scheduler window): each hop continues the W3C ``traceparent`` as a
CHILD context, so every flight-recorder event carries (trace_id,
span_id, parent_id) and the events of one request — harvested from
live ``/debug/traces`` endpoints or post-mortem dump files — re-link
into one ordered tree.  This module is that re-linker, shared by the
router's fan-out stitcher and the ``tools/obs_query.py`` CLI:

- :func:`stitch` groups events by span-id, links spans via parent-id,
  and returns JSON-ready root nodes (events and children ordered by
  wall time — one clock per node's process, same host in practice),
- :func:`render_tree` draws the same tree as indented text for
  terminals.

Events predating the ``parent_id`` stamp (old dump files) still
stitch: they form parentless roots, ordered by time.  Stdlib only.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _as_float(v: object) -> float:
    return float(v) if isinstance(v, (int, float)) else 0.0


def _as_str(v: object) -> str:
    return v if isinstance(v, str) else ""


class _Node:
    __slots__ = ("span_id", "parent_id", "source", "events",
                 "children")

    def __init__(self, span_id: str) -> None:
        self.span_id = span_id
        self.parent_id = ""
        self.source = ""
        self.events: List[Dict[str, object]] = []
        self.children: List["_Node"] = []

    def t0(self) -> float:
        return min((_as_float(e.get("t_wall")) for e in self.events),
                   default=0.0)

    def to_dict(self) -> Dict[str, object]:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "source": self.source,
            "events": self.events,
            "children": [c.to_dict() for c in self.children],
        }


def stitch(events: List[Dict[str, object]]
           ) -> List[Dict[str, object]]:
    """Re-link one trace's *events* (dicts in the flight-recorder
    shape, possibly from several processes) into span-tree roots.
    Each node: ``{span_id, parent_id, source, events, children}``
    with events and children ordered by wall time."""
    nodes: Dict[str, _Node] = {}
    for ev in events:
        sid = _as_str(ev.get("span_id"))
        node = nodes.get(sid)
        if node is None:
            node = nodes[sid] = _Node(sid)
        node.events.append(ev)
        pid = _as_str(ev.get("parent_id"))
        if pid:
            node.parent_id = pid
        src = _as_str(ev.get("source"))
        if src:
            node.source = src
    roots: List[_Node] = []
    for node in nodes.values():
        node.events.sort(key=lambda e: _as_float(e.get("t_wall")))
        parent = nodes.get(node.parent_id) if node.parent_id else None
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node.children.sort(key=lambda n: n.t0())
    roots.sort(key=lambda n: n.t0())
    return [r.to_dict() for r in roots]


def event_severity(ev: Dict[str, object]) -> str:
    """An event's severity tag: top-level ``severity`` when present,
    else the ``severity`` attr alert-transition journal entries carry
    (PR 18).  Empty string for everything unsevere."""
    sev = ev.get("severity")
    if isinstance(sev, str) and sev:
        return sev
    attrs = ev.get("attrs")
    if isinstance(attrs, dict):
        sev = attrs.get("severity")
        if isinstance(sev, str):
            return sev
    return ""


def flatten(tree: List[Dict[str, object]]) -> List[Dict[str, object]]:
    """Depth-first event list of a stitched tree — the causal order a
    test (or a grep) walks: a parent span's events come before its
    children's.  Events carrying a severity (alert transitions) gain
    a top-level ``severity`` key so downstream renderers and filters
    never dig through attrs."""
    out: List[Dict[str, object]] = []

    def walk(node: Dict[str, object]) -> None:
        evs = node.get("events")
        if isinstance(evs, list):
            for e in evs:
                if not isinstance(e, dict):
                    continue
                sev = event_severity(e)
                out.append({**e, "severity": sev} if sev else e)
        children = node.get("children")
        if isinstance(children, list):
            for c in children:
                if isinstance(c, dict):
                    walk(c)

    for root in tree:
        walk(root)
    return out


def render_tree(tree: List[Dict[str, object]],
                t_base: Optional[float] = None) -> str:
    """Indented text rendering of a stitched tree (the obs_query CLI's
    output).  Event times print relative to the trace's first event."""
    lines: List[str] = []
    if t_base is None:
        stamps = [_as_float(e.get("t_wall")) for e in flatten(tree)]
        t_base = min((s for s in stamps if s > 0), default=0.0)

    def walk(node: Dict[str, object], depth: int) -> None:
        pad = "  " * depth
        sid = _as_str(node.get("span_id")) or "(no span)"
        src = _as_str(node.get("source"))
        evs = node.get("events")
        n = len(evs) if isinstance(evs, list) else 0
        head = f"{pad}span {sid[:16]}"
        if src:
            head += f" [{src}]"
        lines.append(f"{head} ({n} events)")
        if isinstance(evs, list):
            for ev in evs:
                if not isinstance(ev, dict):
                    continue
                dt = _as_float(ev.get("t_wall")) - (t_base or 0.0)
                name = _as_str(ev.get("name"))
                attrs = ev.get("attrs")
                extra = ""
                if isinstance(attrs, dict):
                    dur = attrs.get("duration_s")
                    if isinstance(dur, (int, float)):
                        extra = f" duration_s={dur:.6f}"
                    out = attrs.get("outcome")
                    if isinstance(out, str):
                        extra += f" outcome={out}"
                sev = event_severity(ev)
                if sev:
                    extra += f" severity={sev}"
                lines.append(f"{pad}  +{dt:9.4f}s {name}{extra}")
        children = node.get("children")
        if isinstance(children, list):
            for c in children:
                if isinstance(c, dict):
                    walk(c, depth + 1)

    for root in tree:
        walk(root, 0)
    return "\n".join(lines)
