"""Multi-window multi-burn-rate alerting over the in-process TSDB.

The Google SRE workbook's alerting chapter, dep-free: each ``--slo``
class derives a **page** rule (error-budget burn >= 14.4x over the
short AND the long window — fast enough to catch a collapse, two
windows so a single noisy scrape cannot page) and a **ticket** rule
(burn >= 1x over six hours — the budget is on track to be gone), and
operators add hand-written threshold rules from a ``--alert-rules``
JSON file.  Expressions are the :mod:`.tsdb` grammar, so every rule is
also a ``/debug/query`` you can run by hand.

Each rule owns one state machine::

    inactive -> pending -(for: dwell)-> firing -> resolved -> inactive

Every transition journals to the PR-4 flight recorder (event
``tpu_alert_transition`` with a ``severity`` attr — post-mortem dumps
sort and color on it) and the evaluator exports
``tpu_alert_state{alert,severity}`` (0=inactive 1=pending 2=firing
3=resolved), ``tpu_alert_transitions_total{alert,severity}`` and
``tpu_alert_evaluations_total``.  ``/alerts`` serves :meth:`status`;
replica ``/statz`` embeds :meth:`brief` so the router's cached poll
carries alert state fleet-wide with no extra fan-out.

Alert *names* become label values, so they are bounded by the rule set
(never request-controlled), same discipline as :mod:`.slo`.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Tuple,
)

from .core import Registry
from .recorder import FlightRecorder
from .slo import SLOPolicy
from .tsdb import TSDB, Expr, format_duration, parse_expr

log = logging.getLogger(__name__)

# state machine positions (and their tpu_alert_state gauge coding)
STATE_INACTIVE = "inactive"
STATE_PENDING = "pending"
STATE_FIRING = "firing"
STATE_RESOLVED = "resolved"
STATE_VALUE: Dict[str, int] = {
    STATE_INACTIVE: 0, STATE_PENDING: 1, STATE_FIRING: 2,
    STATE_RESOLVED: 3,
}

# severity routing classes (page wakes a human, ticket waits for
# business hours, info is dashboard-only)
SEVERITY_PAGE = "page"
SEVERITY_TICKET = "ticket"
SEVERITY_INFO = "info"
SEVERITIES = (SEVERITY_PAGE, SEVERITY_TICKET, SEVERITY_INFO)

# the SRE-workbook burn-rate table (objective-independent):
# page when 2% of a 30d budget burns in 1h  -> 14.4x over 5m AND 1h
# ticket when burning exactly at budget     -> 1x over 6h
PAGE_BURN_RATE = 14.4
TICKET_BURN_RATE = 1.0
PAGE_SHORT_WINDOW_S = 300.0
PAGE_LONG_WINDOW_S = 3600.0
TICKET_WINDOW_S = 21600.0

# journal event name for every state transition
ALERT_TRANSITION_EVENT = "tpu_alert_transition"

# how long a resolved alert stays visible on /alerts before returning
# to inactive (an operator must be able to see what just resolved)
DEFAULT_RESOLVED_HOLD_S = 300.0

_ALERT_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_.:-]*$")
_OPS = (">", ">=", "<", "<=")


@dataclass(frozen=True)
class AlertCondition:
    """One ``expr op threshold`` clause; a rule fires only when every
    clause holds (multi-window AND)."""

    expr: str
    op: str = ">"
    threshold: float = 0.0

    def __post_init__(self) -> None:
        if self.op not in _OPS:
            raise ValueError(
                f"bad op {self.op!r} (want one of {_OPS})")
        parse_expr(self.expr)  # malformed rules fail at load, not 3am

    def holds(self, value: float) -> bool:
        if self.op == ">":
            return value > self.threshold
        if self.op == ">=":
            return value >= self.threshold
        if self.op == "<":
            return value < self.threshold
        return value <= self.threshold


@dataclass(frozen=True)
class AlertRule:
    """One alert: ANDed conditions, a ``for:`` dwell, a severity."""

    name: str
    conditions: Tuple[AlertCondition, ...]
    severity: str = SEVERITY_TICKET
    for_s: float = 0.0
    description: str = ""

    def __post_init__(self) -> None:
        if not _ALERT_NAME_RE.match(self.name):
            raise ValueError(f"bad alert name {self.name!r}")
        if not self.conditions:
            raise ValueError(f"alert {self.name!r} needs >= 1 condition")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"bad severity {self.severity!r} on {self.name!r} "
                f"(want one of {SEVERITIES})")
        if self.for_s < 0:
            raise ValueError(f"for_s must be >= 0 on {self.name!r}")


def threshold_rule(name: str, expr: str, op: str, threshold: float, *,
                   for_s: float = 0.0,
                   severity: str = SEVERITY_TICKET,
                   description: str = "") -> AlertRule:
    """Single-condition convenience constructor."""
    return AlertRule(name, (AlertCondition(expr, op, threshold),),
                     severity=severity, for_s=for_s,
                     description=description)


def burn_rate_rules(policies: Mapping[str, SLOPolicy], *,
                    metric: str = "tpu_slo_error_budget_burn_rate",
                    label: str = "class",
                    window_scale: float = 1.0,
                    page_burn: float = PAGE_BURN_RATE,
                    ticket_burn: float = TICKET_BURN_RATE
                    ) -> List[AlertRule]:
    """Derive the SRE multi-window multi-burn-rate rule pair for every
    SLO class.  *metric* is the instantaneous burn gauge to smooth
    (the replica uses the accountant's gauge; the router points this
    at its fleet-aggregate bridge gauge).  *window_scale* shrinks the
    canonical 5m/1h/6h windows so CI and soak tests traverse the full
    state machine in seconds of wall time."""
    if window_scale <= 0:
        raise ValueError("window_scale must be > 0")
    short_w = format_duration(PAGE_SHORT_WINDOW_S * window_scale)
    long_w = format_duration(PAGE_LONG_WINDOW_S * window_scale)
    ticket_w = format_duration(TICKET_WINDOW_S * window_scale)
    rules: List[AlertRule] = []
    for name in sorted(policies):
        sel = f'{metric}{{{label}="{name}"}}'
        rules.append(AlertRule(
            f"slo_burn_page_{name}",
            (AlertCondition(f"avg_over_time({sel}[{short_w}])",
                            ">=", page_burn),
             AlertCondition(f"avg_over_time({sel}[{long_w}])",
                            ">=", page_burn)),
            severity=SEVERITY_PAGE,
            description=(
                f"SLO class {name!r} is burning error budget at >= "
                f"{page_burn}x over both {short_w} and {long_w} — at "
                "this rate a 30d budget is gone within hours."),
        ))
        rules.append(AlertRule(
            f"slo_burn_ticket_{name}",
            (AlertCondition(f"avg_over_time({sel}[{ticket_w}])",
                            ">=", ticket_burn),),
            severity=SEVERITY_TICKET,
            description=(
                f"SLO class {name!r} has burned at >= {ticket_burn}x "
                f"budget for {ticket_w}: on track to exhaust the "
                "window's error budget."),
        ))
    return rules


def burn_rate(total: float, missed: float, objective: float) -> float:
    """The burn-rate definition everything above applies: observed
    miss rate over the budgeted miss rate.  Exposed so tests can
    hand-compute windows against the rule thresholds."""
    if not 0.0 < objective < 1.0:
        raise ValueError("objective must be in (0, 1)")
    if total <= 0:
        return 0.0
    return (missed / total) / (1.0 - objective)


# -- --alert-rules JSON ------------------------------------------------------

def parse_alert_rules(text: str) -> List[AlertRule]:
    """Parse the ``--alert-rules`` JSON document::

        {"rules": [
          {"name": "queue_deep", "expr": "tpu_serve_queue_depth",
           "op": ">", "threshold": 100, "for_s": 60,
           "severity": "ticket", "description": "..."},
          {"name": "multi", "severity": "page", "for_s": 0,
           "conditions": [
             {"expr": "rate(tpu_serve_errors_total[1m])",
              "op": ">", "threshold": 0.5},
             {"expr": "rate(tpu_serve_errors_total[10m])",
              "op": ">", "threshold": 0.5}]}
        ]}

    Either a flat ``expr/op/threshold`` triple or an explicit
    ``conditions`` list; raises ValueError on anything malformed."""
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"alert rules: bad JSON: {e}") from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("rules"), list):
        raise ValueError('alert rules: want {"rules": [...]}')
    rules: List[AlertRule] = []
    for i, raw in enumerate(doc["rules"]):
        if not isinstance(raw, dict):
            raise ValueError(f"alert rules[{i}]: want an object")
        name = raw.get("name")
        if not isinstance(name, str):
            raise ValueError(f"alert rules[{i}]: missing name")
        conds: List[AlertCondition] = []
        if "conditions" in raw:
            if not isinstance(raw["conditions"], list):
                raise ValueError(f"alert {name!r}: conditions must "
                                 "be a list")
            for c in raw["conditions"]:
                if not isinstance(c, dict) or "expr" not in c:
                    raise ValueError(
                        f"alert {name!r}: each condition needs expr")
                conds.append(AlertCondition(
                    str(c["expr"]), str(c.get("op", ">")),
                    float(c.get("threshold", 0.0))))
        elif "expr" in raw:
            conds.append(AlertCondition(
                str(raw["expr"]), str(raw.get("op", ">")),
                float(raw.get("threshold", 0.0))))
        else:
            raise ValueError(
                f"alert {name!r}: needs expr or conditions")
        rules.append(AlertRule(
            name, tuple(conds),
            severity=str(raw.get("severity", SEVERITY_TICKET)),
            for_s=float(raw.get("for_s", 0.0)),
            description=str(raw.get("description", ""))))
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError("alert rules: duplicate rule names")
    return rules


def load_alert_rules(path: str) -> List[AlertRule]:
    with open(path, "r", encoding="utf-8") as f:
        return parse_alert_rules(f.read())


# -- evaluator ---------------------------------------------------------------

class _RuleState:
    __slots__ = ("state", "since", "pending_since", "firing_since",
                 "resolved_since", "value", "cond_values")

    def __init__(self) -> None:
        self.state = STATE_INACTIVE
        self.since = 0.0
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.resolved_since: Optional[float] = None
        self.value: Optional[float] = None
        self.cond_values: List[Optional[float]] = []


@dataclass(frozen=True)
class _CompiledRule:
    rule: AlertRule
    exprs: Tuple[Expr, ...] = field(default=())


class AlertEvaluator:
    """Evaluate a fixed rule set against one TSDB on every tick.

    Registers itself as a TSDB tick hook, so a live surface needs only
    ``TSDB.start()``; tests drive ``tsdb.tick(now=...)`` (or
    :meth:`evaluate` directly) under a fake clock."""

    def __init__(self, tsdb: TSDB, rules: Iterable[AlertRule], *,
                 registry: Optional[Registry] = None,
                 recorder: Optional[FlightRecorder] = None,
                 resolved_hold_s: float = DEFAULT_RESOLVED_HOLD_S
                 ) -> None:
        self._tsdb = tsdb
        self._recorder = recorder
        self._resolved_hold_s = float(resolved_hold_s)
        self._lock = threading.Lock()
        # transition hooks (PR 19: incident bundles subscribe here).
        # Transitions are queued under the lock and hooks fire AFTER
        # it releases — a subscriber may call back into status()/
        # firing() (which take the lock) without deadlocking, and a
        # slow subscriber can never stall rule evaluation itself.
        self._hooks: List[Callable[
            [AlertRule, str, str, float, Optional[float]], None]] = []
        self._pending_hooks: List[
            Tuple[AlertRule, str, str, float, Optional[float]]] = []
        self._rules: List[_CompiledRule] = []
        seen: Dict[str, bool] = {}
        for rule in rules:
            if rule.name in seen:
                raise ValueError(f"duplicate alert rule {rule.name!r}")
            seen[rule.name] = True
            self._rules.append(_CompiledRule(
                rule, tuple(parse_expr(c.expr)
                            for c in rule.conditions)))
        self._state: Dict[str, _RuleState] = {
            c.rule.name: _RuleState() for c in self._rules}
        reg = registry if registry is not None else tsdb.registry
        self._g_state = reg.gauge(
            "tpu_alert_state",
            "Alert state machine position by alert and severity "
            "(0=inactive, 1=pending, 2=firing, 3=resolved).",
            ("alert", "severity"))
        self._c_transitions = reg.counter(
            "tpu_alert_transitions_total",
            "Alert state-machine transitions by alert and severity "
            "(every transition also journals to the flight recorder).",
            ("alert", "severity"))
        self._c_evaluations = reg.counter(
            "tpu_alert_evaluations_total",
            "Alert rule evaluation passes run by this evaluator.")
        # materialize every rule's series at boot: dashboards and the
        # promlint smoke see one schema whether anything fired or not
        for c in self._rules:
            self._g_state.labels(
                alert=c.rule.name, severity=c.rule.severity).set(0.0)
        tsdb.add_tick_hook(self.evaluate)

    @property
    def rules(self) -> List[AlertRule]:
        return [c.rule for c in self._rules]

    def add_transition_hook(
            self, fn: Callable[
                [AlertRule, str, str, float, Optional[float]],
                None]) -> None:
        """Subscribe to state-machine transitions.  *fn* is called as
        ``fn(rule, state_from, state_to, at, value)`` after every
        transition, outside the evaluator lock; exceptions are logged
        and never reach rule evaluation."""
        with self._lock:
            self._hooks.append(fn)

    # -- evaluation ----------------------------------------------------------

    def _condition_value(self, expr: Expr, cond: AlertCondition,
                         at: float) -> Optional[float]:
        """The most-breaching value across matching series (any-series
        semantics: one bad replica class breaches the rule)."""
        results = self._tsdb.evaluate(expr, at=at)
        if not results:
            return None
        values = [v for _, v in results]
        return max(values) if cond.op in (">", ">=") else min(values)

    def evaluate(self, now: Optional[float] = None) -> None:
        at = self._tsdb.now() if now is None else float(now)
        self._c_evaluations.inc()
        with self._lock:
            for c in self._rules:
                self._evaluate_rule_locked(c, at)
            fired = self._pending_hooks
            self._pending_hooks = []
            hooks = list(self._hooks)
        # hooks run outside the lock (see __init__) — a subscriber may
        # read evaluator state and must not be able to wedge the tick
        for rule, old, new, t, value in fired:
            for fn in hooks:
                try:
                    fn(rule, old, new, t, value)
                except Exception:
                    log.exception(
                        "alert transition hook failed for %s",
                        rule.name)

    def _evaluate_rule_locked(self, c: _CompiledRule,
                              at: float) -> None:
        rule = c.rule
        st = self._state[rule.name]
        cond_values: List[Optional[float]] = []
        breach = True
        for expr, cond in zip(c.exprs, rule.conditions):
            val = self._condition_value(expr, cond, at)
            cond_values.append(val)
            if val is None or not cond.holds(val):
                breach = False
        st.cond_values = cond_values
        st.value = cond_values[0] if cond_values else None
        if breach:
            if st.state in (STATE_INACTIVE, STATE_RESOLVED):
                st.pending_since = at
                self._transition_locked(rule, st, STATE_PENDING, at)
            if st.state == STATE_PENDING and \
                    st.pending_since is not None and \
                    at - st.pending_since >= rule.for_s:
                st.firing_since = at
                self._transition_locked(rule, st, STATE_FIRING, at)
        else:
            if st.state == STATE_PENDING:
                self._transition_locked(rule, st, STATE_INACTIVE, at)
            elif st.state == STATE_FIRING:
                st.resolved_since = at
                self._transition_locked(rule, st, STATE_RESOLVED, at)
            elif st.state == STATE_RESOLVED and \
                    st.resolved_since is not None and \
                    at - st.resolved_since >= self._resolved_hold_s:
                self._transition_locked(rule, st, STATE_INACTIVE, at)

    def _transition_locked(self, rule: AlertRule, st: _RuleState,
                           new: str, at: float) -> None:
        old = st.state
        st.state = new
        st.since = at
        if new == STATE_INACTIVE:
            st.pending_since = None
            st.firing_since = None
            st.resolved_since = None
        self._g_state.labels(
            alert=rule.name, severity=rule.severity).set(
                float(STATE_VALUE[new]))
        self._c_transitions.labels(
            alert=rule.name, severity=rule.severity).inc()
        if self._recorder is not None:
            self._recorder.record(
                ALERT_TRANSITION_EVENT,
                alert=rule.name, severity=rule.severity,
                state_from=old, state_to=new, at=at,
                value=(st.value if st.value is not None else ""))
        self._pending_hooks.append((rule, old, new, at, st.value))

    # -- read paths ----------------------------------------------------------

    def firing(self, severity: Optional[str] = None) -> List[str]:
        """Names of currently-firing alerts, optionally by severity."""
        with self._lock:
            out: List[str] = []
            for c in self._rules:
                st = self._state[c.rule.name]
                if st.state != STATE_FIRING:
                    continue
                if severity is not None and \
                        c.rule.severity != severity:
                    continue
                out.append(c.rule.name)
            return out

    def status(self, now: Optional[float] = None) -> Dict[str, object]:
        """The ``GET /alerts`` payload: every rule with its machine
        position, condition values, and timing."""
        at = self._tsdb.now() if now is None else float(now)
        alerts: List[Dict[str, object]] = []
        counts = {s: 0 for s in STATE_VALUE}
        with self._lock:
            for c in self._rules:
                rule = c.rule
                st = self._state[rule.name]
                counts[st.state] += 1
                alerts.append({
                    "name": rule.name,
                    "severity": rule.severity,
                    "state": st.state,
                    "state_value": STATE_VALUE[st.state],
                    "since": st.since,
                    "for_s": rule.for_s,
                    "firing_since": st.firing_since,
                    "value": st.value,
                    "description": rule.description,
                    "conditions": [
                        {"expr": cond.expr, "op": cond.op,
                         "threshold": cond.threshold, "value": val}
                        for cond, val in zip(
                            rule.conditions,
                            st.cond_values or
                            [None] * len(rule.conditions))],
                })
        return {
            "now": at,
            "alerts": alerts,
            "firing": [a["name"] for a in alerts
                       if a["state"] == STATE_FIRING],
            "counts": counts,
        }

    def brief(self) -> Dict[str, object]:
        """Compact block for ``/statz`` embedding (the router's cached
        replica poll carries it fleet-wide for free)."""
        with self._lock:
            firing = []
            pending = 0
            for c in self._rules:
                st = self._state[c.rule.name]
                if st.state == STATE_FIRING:
                    firing.append({
                        "name": c.rule.name,
                        "severity": c.rule.severity,
                        "since": st.since,
                    })
                elif st.state == STATE_PENDING:
                    pending += 1
            return {
                "firing": firing,
                "pending": pending,
                "firing_page": sum(
                    1 for f in firing if f["severity"] == SEVERITY_PAGE),
            }

    def status_json(self, now: Optional[float] = None) -> str:
        return json.dumps(self.status(now), sort_keys=True)
