"""Dependency-free metrics core: the ONE registry/renderer every HTTP
surface in this repo exposes Prometheus metrics through.

Before this module the repo carried three divergent hand-rolled text
renderers (plugin debug endpoint, health exporter, serving server), no
histograms, and a cross-module private import for label escaping.  This
is the common substrate they all rewire onto:

- labeled :class:`Counter` / :class:`Gauge` / :class:`Histogram`
  families with fixed bucket schemes,
- a thread-safe :class:`Registry` with get-or-create instrument
  constructors and render-time collector callbacks,
- one promlint-clean text-exposition renderer (``# HELP`` + ``# TYPE``
  for every family, counters forced to end in ``_total``, histogram
  ``_bucket``/``_sum``/``_count`` triples with a ``+Inf`` bucket),
- parsing + quantile-estimation helpers so benchmarks and tests can
  read latency percentiles back out of a scraped exposition body.

Stdlib only, by design: the exporter daemon and slice layer must stay
importable on a bare grpc+protobuf image, and client-library registry
state must never leak between tests (every surface owns its Registry
instance; there is deliberately NO process-global default registry).
"""

from __future__ import annotations

import logging
import math
import os
import re
import threading
import time
from bisect import bisect_left
from typing import (
    Any,
    Callable,
    Dict,
    Generic,
    Iterable,
    List,
    Optional,
    Tuple,
    TypeVar,
    cast,
)

log = logging.getLogger(__name__)

# Content types for the two exposition modes.  Every /metrics handler
# negotiates via the Accept header: the OpenMetrics type unlocks
# exemplars (last trace-id per histogram bucket) and the `# EOF`
# terminator; the default text exposition stays byte-identical to
# pre-exemplar output so promlint and existing scrapes never change.
TEXT_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")


def negotiate_openmetrics(accept: Optional[str]) -> bool:
    """True when the Accept header asks for the OpenMetrics exposition
    (what a Prometheus server scraping with exemplar support sends)."""
    return accept is not None and "application/openmetrics-text" in accept

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Fixed bucket schemes (seconds).  Shared across surfaces so the same
# dashboard query shape works on every histogram; pick by time scale:
#
# FAST_BUCKETS_S   sub-millisecond .. 1s: per-token decode, stream
#                  writes, ListAndWatch frame builds, sysfs probes
# LATENCY_BUCKETS_S  1ms .. 60s: request latency, TTFT, queue wait
# SLOW_BUCKETS_S   100ms .. 10min: slice join/formation
FAST_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
SLOW_BUCKETS_S = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline).

    The one copy of the escaping rule: ``health.metrics`` and the
    plugin debug renderer used to each carry their own (one reaching
    into the other's private ``_escape``)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def escape_help(v: str) -> str:
    """HELP-line escaping (backslash and newline only, per exposition
    format — quotes are legal in help text)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    """Sample value formatting: integers render bare (promtool-friendly
    and diff-stable), floats via repr (full precision)."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (math.inf, -math.inf):
        return "+Inf" if f > 0 else "-Inf"
    if f.is_integer() and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _fmt_le(bound: float) -> str:
    """``le`` label formatting: +Inf for the top bucket, shortest exact
    decimal otherwise (0.005, not 0.005000000000000001)."""
    if bound == math.inf:
        return "+Inf"
    return format(bound, "g")


class _Child:
    """One labeled series of a counter/gauge family."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only go up; use a Gauge")
        with self._lock:
            self._value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    # counters bridging pre-existing monotonic ints (engine stats, RPC
    # count dicts) adopt the externally-tracked total at render time
    _set = set


class _HistChild:
    """One labeled series of a histogram family."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, lock: threading.Lock,
                 bounds: Tuple[float, ...]) -> None:
        self._lock = lock
        self._bounds = bounds                 # includes trailing +Inf
        self._counts: List[int] = [0] * len(bounds)
        self._sum = 0.0
        self._count = 0
        # bucket index -> (trace_id, observed value, wall time): the
        # LAST traced observation per bucket, rendered as an
        # OpenMetrics exemplar so a dashboard's slow bucket links to a
        # concrete /debug/traces entry.  None until a traced observe.
        self._exemplars: Optional[Dict[int, Tuple[str, float, float]]] \
            = None

    def observe(self, value: float, trace_id: Optional[str] = None
                ) -> None:
        self.observe_n(value, 1, trace_id=trace_id)

    def observe_n(self, value: float, n: int,
                  trace_id: Optional[str] = None) -> None:
        """Record *n* observations of *value* under one lock hop — the
        per-window token path records a whole window at once."""
        if n < 1:
            return
        i = bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += n
            self._sum += value * n
            self._count += n
            if trace_id:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[i] = (trace_id, value, time.time())

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count

    def exemplars(self) -> Dict[int, Tuple[str, float, float]]:
        with self._lock:
            return dict(self._exemplars) if self._exemplars else {}


# the child type one family hands out: _Child for counters/gauges,
# _HistChild for histograms — generic so strict-typed callers get the
# right .inc()/.observe() surface back from .labels()
_C = TypeVar("_C", _Child, _HistChild)


class _Family(Generic[_C]):
    """Base: one metric family (name, help, kind, label names)."""

    kind: str = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Tuple[str, ...] = ()) -> None:
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        if not help:
            raise ValueError(f"metric {name} needs non-empty help text")
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r} on {name}")
            if ln == "le" and self.kind == "histogram":
                raise ValueError("'le' is reserved on histograms")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _C] = {}

    def _make_child(self) -> _C:
        raise NotImplementedError

    def render(self, out: List[str], openmetrics: bool = False) -> None:
        raise NotImplementedError

    def labels(self, **kv: object) -> _C:
        """Get-or-create the child for one label-value combination."""
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def clear(self) -> None:
        """Drop every child — for snapshot-style families whose label
        sets are rebuilt from scratch each scrape (per-chip health,
        per-member heartbeat age): a vanished chip must not leave a
        stale series behind."""
        with self._lock:
            self._children.clear()

    def _default(self) -> _C:
        return self.labels(**{})

    def _sorted_children(self) -> List[Tuple[Tuple[str, ...], _C]]:
        with self._lock:
            return sorted(self._children.items())


class Counter(_Family[_Child]):
    """Monotonic counter family.  Names MUST end in ``_total`` — the
    renderer is promlint-clean by construction, not by review."""

    kind = "counter"

    def __init__(self, name: str, help: str,
                 labelnames: Tuple[str, ...] = ()) -> None:
        if not name.endswith("_total"):
            raise ValueError(
                f"counter {name!r} must end in '_total' (promlint)")
        super().__init__(name, help, labelnames)

    def _make_child(self) -> _Child:
        return _Child(threading.Lock())

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def _set(self, value: float) -> None:
        """Adopt an externally-tracked monotonic total (bridge path for
        counters whose source of truth predates the registry)."""
        self._default()._set(value)

    @property
    def value(self) -> float:
        return self._default().value

    def render(self, out: List[str], openmetrics: bool = False) -> None:
        for key, child in self._sorted_children():
            out.append(_sample(self.name, self.labelnames, key,
                               child.value))


class Gauge(_Family[_Child]):
    kind = "gauge"

    def _make_child(self) -> _Child:
        return _Child(threading.Lock())

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        child = self._default()
        with child._lock:
            child._value += amount

    @property
    def value(self) -> float:
        return self._default().value

    def render(self, out: List[str], openmetrics: bool = False) -> None:
        for key, child in self._sorted_children():
            out.append(_sample(self.name, self.labelnames, key,
                               child.value))


class Histogram(_Family[_HistChild]):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 labelnames: Tuple[str, ...] = (),
                 buckets: Iterable[float] = LATENCY_BUCKETS_S) -> None:
        bounds = tuple(sorted(set(float(b) for b in buckets)))
        if not bounds:
            raise ValueError(f"histogram {name} needs >= 1 bucket")
        if any(b != b for b in bounds):
            raise ValueError(f"NaN bucket bound on {name}")
        if bounds[-1] != math.inf:
            bounds = bounds + (math.inf,)
        self.buckets = bounds
        super().__init__(name, help, labelnames)

    def _make_child(self) -> _HistChild:
        return _HistChild(threading.Lock(), self.buckets)

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        self._default().observe(value, trace_id=trace_id)

    def observe_n(self, value: float, n: int,
                  trace_id: Optional[str] = None) -> None:
        self._default().observe_n(value, n, trace_id=trace_id)

    @property
    def top_finite_bucket(self) -> float:
        """Highest finite bound — the anchor for slow-span escalation
        (Span's default WARNING threshold is 5x this)."""
        finite = [b for b in self.buckets if b != math.inf]
        return finite[-1] if finite else 0.0

    def render(self, out: List[str], openmetrics: bool = False) -> None:
        for key, child in self._sorted_children():
            counts, total, count = child.snapshot()
            # exemplars render ONLY under the OpenMetrics content type:
            # the plain text exposition must stay byte-compatible with
            # pre-exemplar scrapes (and promlint-clean)
            ex = child.exemplars() if openmetrics else {}
            cum = 0
            for i, (bound, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                line = _sample(
                    self.name + "_bucket",
                    self.labelnames + ("le",),
                    key + (_fmt_le(bound),), cum)
                if i in ex:
                    tid, val, ts = ex[i]
                    line += (f' # {{trace_id="{escape_label_value(tid)}"'
                             f"}} {_fmt_value(val)} {ts:.3f}")
                out.append(line)
            out.append(_sample(self.name + "_sum", self.labelnames,
                               key, total))
            out.append(_sample(self.name + "_count", self.labelnames,
                               key, count))


def _sample(name: str, labelnames: Tuple[str, ...],
            labelvalues: Tuple[str, ...], value: float) -> str:
    if labelnames:
        body = ",".join(
            f'{ln}="{escape_label_value(lv)}"'
            for ln, lv in zip(labelnames, labelvalues))
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


class Registry:
    """Thread-safe family registry + the one exposition renderer.

    Instrument constructors are get-or-create: asking twice for the
    same (name, kind) returns the same family, so a coordinator and a
    client sharing a process share series instead of colliding.  Kind
    or label-set mismatches on an existing name raise — silent type
    drift is how the three old renderers diverged.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family[Any]] = {}
        self._collectors: List[Callable[[], None]] = []
        # the one ProcessCollector this registry carries (see
        # attach_process_collector): tracked here so repeated attaches
        # — e.g. a fresh ScrapeMeta per render — can't stack duplicate
        # on_collect hooks (the collector list has no dedup by design)
        self._process_collector: Optional["ProcessCollector"] = None

    def _get_or_create(self, cls: type, name: str, help: str,
                       labelnames: Iterable[str],
                       **kw: Any) -> "_Family[Any]":
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or \
                        fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}")
                return fam
            made: _Family[Any] = cls(name, help, tuple(labelnames),
                                     **kw)
            self._families[name] = made
            return made

    def counter(self, name: str, help: str,
                labelnames: Tuple[str, ...] = ()) -> Counter:
        return cast(Counter,
                    self._get_or_create(Counter, name, help, labelnames))

    def gauge(self, name: str, help: str,
              labelnames: Tuple[str, ...] = ()) -> Gauge:
        return cast(Gauge,
                    self._get_or_create(Gauge, name, help, labelnames))

    def histogram(self, name: str, help: str,
                  labelnames: Tuple[str, ...] = (),
                  buckets: Iterable[float] = LATENCY_BUCKETS_S
                  ) -> Histogram:
        return cast(Histogram,
                    self._get_or_create(Histogram, name, help,
                                        labelnames, buckets=buckets))

    def on_collect(self, fn: Callable[[], None]) -> None:
        """Register a callback run at the top of every render() — the
        hook snapshot-style surfaces use to refresh gauges (manager
        status, heartbeat ages) right before the scrape reads them."""
        with self._lock:
            self._collectors.append(fn)

    def render(self, openmetrics: bool = False) -> str:
        """The whole registry in exposition format.  Plain mode is the
        Prometheus text format, unchanged.  *openmetrics* adds histogram
        exemplars (last trace-id per bucket) and the ``# EOF``
        terminator — serve it only under
        :data:`OPENMETRICS_CONTENT_TYPE` (see
        :func:`negotiate_openmetrics`) so plain-text scrapers never see
        an exemplar."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                # a broken collector degrades one scrape's freshness,
                # never the scrape itself
                log.exception("metrics collector failed")
        # snapshot the family list only AFTER the collectors ran: a
        # hook that lazily registers its instruments on first call must
        # still see them rendered in that same (first) scrape
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        out: List[str] = []
        for fam in families:
            samples: List[str] = []
            fam.render(samples, openmetrics=openmetrics)
            if not samples:
                continue
            out.append(f"# HELP {fam.name} {escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            out.extend(samples)
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"


class ProcessCollector:
    """Standard process self-metrics, read at scrape time (dep-free).

    Every ``/metrics`` surface answers the same first incident
    questions — is the process leaking memory, burning CPU, or
    exhausting file descriptors — through four conventional families:

    - ``tpu_process_cpu_seconds_total``  user+system CPU (os.times)
    - ``tpu_process_rss_bytes``          resident set (/proc/self/statm)
    - ``tpu_process_open_fds``           open descriptors (/proc/self/fd)
    - ``tpu_process_start_time_seconds`` epoch start (/proc/self/stat)

    Values refresh lazily via :meth:`Registry.on_collect` — no
    background thread, no cost between scrapes.  Where ``/proc`` is
    missing (macOS dev boxes, odd containers) the affected family
    degrades to its last value instead of breaking the scrape.

    Use :func:`attach_process_collector` (idempotent per registry)
    rather than constructing directly: ``Registry.on_collect`` appends
    without dedup, so a second construction would double-register.
    """

    def __init__(self, registry: "Registry") -> None:
        self._c_cpu = registry.counter(
            "tpu_process_cpu_seconds_total",
            "Total user and system CPU time this process has "
            "consumed, in seconds.")
        self._g_rss = registry.gauge(
            "tpu_process_rss_bytes",
            "Resident set size of this process in bytes.")
        self._g_fds = registry.gauge(
            "tpu_process_open_fds",
            "File descriptors currently open in this process.")
        self._g_start = registry.gauge(
            "tpu_process_start_time_seconds",
            "Start time of this process, seconds since the unix "
            "epoch.")
        self._page_size = 4096
        try:
            self._page_size = os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError, AttributeError):
            pass
        self._g_start.set(self._read_start_time())
        registry.on_collect(self._collect)

    @staticmethod
    def _read_start_time() -> float:
        """Process start epoch: kernel boot time (/proc/stat btime)
        plus the process start offset (/proc/self/stat field 22, in
        clock ticks).  Falls back to 'now' at attach time — surfaces
        attach at boot, so the error is bounded by startup cost."""
        try:
            btime = None
            with open("/proc/stat", encoding="ascii") as f:
                for line in f:
                    if line.startswith("btime "):
                        btime = float(line.split()[1])
                        break
            with open("/proc/self/stat", encoding="ascii") as f:
                stat = f.read()
            # field 2 (comm) may contain spaces; split after its ')'
            ticks = float(stat.rsplit(")", 1)[1].split()[19])
            hz = os.sysconf("SC_CLK_TCK")
            if btime is not None and hz > 0:
                return btime + ticks / hz
        except (OSError, ValueError, IndexError, AttributeError):
            pass
        return time.time()

    def _collect(self) -> None:
        t = os.times()
        self._c_cpu._set(float(t.user + t.system))
        try:
            with open("/proc/self/statm", encoding="ascii") as f:
                self._g_rss.set(
                    float(f.read().split()[1]) * self._page_size)
        except (OSError, ValueError, IndexError):
            pass
        try:
            self._g_fds.set(float(len(os.listdir("/proc/self/fd"))))
        except OSError:
            pass


# attach serialization: construction registers an on_collect hook, so
# two racing attaches must not both construct (the hook list does not
# dedup).  A module lock is the simplest correct gate — construction
# itself takes registry._lock via counter()/gauge()/on_collect().
_PROCESS_ATTACH_LOCK = threading.Lock()


def attach_process_collector(registry: "Registry") -> ProcessCollector:
    """Get-or-create the registry's :class:`ProcessCollector`.

    Idempotent — safe to call from every ScrapeMeta construction even
    on surfaces that build a fresh ScrapeMeta per render."""
    with _PROCESS_ATTACH_LOCK:
        if registry._process_collector is None:
            registry._process_collector = ProcessCollector(registry)
        return registry._process_collector


class ScrapeMeta:
    """Scrape self-observability for one ``/metrics`` surface.

    Wraps :meth:`Registry.render` and records, about the exposition it
    just produced: wall time (``tpu_scrape_duration_seconds``), sample
    lines (``tpu_scrape_series``) and body bytes
    (``tpu_scrape_size_bytes``), each by exposition ``mode``
    (``text``/``openmetrics``).  Values land in the *next* scrape —
    the standard self-scrape convention (a scrape cannot contain its
    own duration).  One instance per surface, created next to the
    surface's Registry.
    """

    def __init__(self, registry: "Registry") -> None:
        self._registry = registry
        # every /metrics surface carries the standard process
        # self-metrics: ScrapeMeta construction is the one chokepoint
        # all four surfaces already pass through, and the attach is
        # idempotent per registry
        attach_process_collector(registry)
        self._h_duration = registry.histogram(
            "tpu_scrape_duration_seconds",
            "Wall time spent rendering this surface's own /metrics "
            "exposition, by exposition mode.",
            ("mode",), buckets=FAST_BUCKETS_S)
        self._g_series = registry.gauge(
            "tpu_scrape_series",
            "Sample lines in this surface's most recent /metrics "
            "exposition, by exposition mode.",
            ("mode",))
        self._g_size = registry.gauge(
            "tpu_scrape_size_bytes",
            "Byte size of this surface's most recent /metrics "
            "exposition body, by exposition mode.",
            ("mode",))
        # render from boot: the very FIRST scrape already carries both
        # mode children (zeroed), so the schema never shifts between
        # scrape 1 and scrape 2
        for mode in ("text", "openmetrics"):
            self._h_duration.labels(mode=mode)
            self._g_series.labels(mode=mode).set(0.0)
            self._g_size.labels(mode=mode).set(0.0)

    def render(self, openmetrics: bool = False) -> str:
        """Render the registry and account the render itself."""
        t0 = time.perf_counter()
        body = self._registry.render(openmetrics=openmetrics)
        duration = time.perf_counter() - t0
        mode = "openmetrics" if openmetrics else "text"
        series = sum(1 for line in body.splitlines()
                     if line and not line.startswith("#"))
        self._h_duration.labels(mode=mode).observe(duration)
        self._g_series.labels(mode=mode).set(float(series))
        self._g_size.labels(mode=mode).set(
            float(len(body.encode("utf-8"))))
        return body


# -- reading expositions back (benchmarks, lint, tests) ---------------------

def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse exposition text into (name, labels, value) samples.
    Comment/blank lines are skipped; malformed sample lines raise."""
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, labels, value = _parse_sample(line)
        samples.append((name, labels, value))
    return samples


def _parse_sample(line: str) -> Tuple[str, Dict[str, str], float]:
    m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)", line)
    if not m:
        raise ValueError(f"malformed sample line: {line!r}")
    name = m.group(1)
    rest = line[m.end():]
    labels: Dict[str, str] = {}
    if rest.startswith("{"):
        i = 1
        while True:
            while i < len(rest) and rest[i] in ", ":
                i += 1
            if i < len(rest) and rest[i] == "}":
                i += 1
                break
            lm = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', rest[i:])
            if not lm:
                raise ValueError(f"malformed labels in: {line!r}")
            ln = lm.group(1)
            i += lm.end()
            buf: List[str] = []
            while i < len(rest):
                c = rest[i]
                if c == "\\":
                    nxt = rest[i + 1:i + 2]
                    buf.append({"\\": "\\", '"': '"', "n": "\n"}.get(
                        nxt, "\\" + nxt))
                    i += 2
                elif c == '"':
                    i += 1
                    break
                else:
                    buf.append(c)
                    i += 1
            else:
                raise ValueError(f"unterminated label value in: {line!r}")
            labels[ln] = "".join(buf)
        rest = rest[i:]
    parts = rest.split()
    if not parts:
        raise ValueError(f"sample line has no value: {line!r}")
    val = parts[0]
    if val == "+Inf":
        fval = math.inf
    elif val == "-Inf":
        fval = -math.inf
    else:
        fval = float(val)
    return name, labels, fval


def histogram_quantile(
    samples: List[Tuple[str, Dict[str, str], float]],
    name: str,
    q: float,
    match: Optional[Dict[str, str]] = None,
) -> float:
    """Estimate quantile *q* of histogram *name* from parsed exposition
    samples (linear interpolation inside the bucket, the same estimate
    PromQL's histogram_quantile makes).  ``match`` filters by label
    subset; children passing the filter are aggregated.  Returns NaN
    when the histogram is absent or empty."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    by_le: Dict[float, float] = {}
    for sname, labels, value in samples:
        if sname != name + "_bucket" or "le" not in labels:
            continue
        if match and any(labels.get(k) != v for k, v in match.items()):
            continue
        le = math.inf if labels["le"] == "+Inf" else float(labels["le"])
        by_le[le] = by_le.get(le, 0.0) + value
    if not by_le or math.inf not in by_le:
        return math.nan
    total = by_le[math.inf]
    if total <= 0:
        return math.nan
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound in sorted(by_le):
        cum = by_le[bound]
        if cum >= target:
            if bound == math.inf:
                return prev_bound  # PromQL: highest finite bound
            if cum == prev_cum:
                return bound
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = bound, cum
    return prev_bound
