"""Bounded in-process time-series retention over the metrics registry.

Every surface in this repo is *point-in-time*: ``/metrics`` renders the
instant a scraper asks, ``/statz`` snapshots now, and "is this getting
worse" needs an external Prometheus.  This module is the missing
retention layer, dep-free like the rest of :mod:`obs`:

- :class:`TSDB` samples an :class:`~.core.Registry` on a tick (a
  background thread in production, a fake-clock ``tick(now)`` in
  tests), re-using :func:`~.core.parse_exposition` on the one renderer
  so the TSDB sees exactly what a scraper would — collect hooks
  included.
- Storage is a **fixed memory budget**: per-series raw ring (high-res
  recent window) plus downsampled tiers (last-sample-per-aligned-bucket
  — which preserves counter monotonicity across tier boundaries), a
  hard series cap with an observable drop counter, and bounded points
  per ring.  No allocation grows with uptime.
- A small recording-rule engine evaluates ``rate()``, ``increase()``,
  ``avg/min/max_over_time()`` and ``histogram_quantile()`` over the
  retained windows — the grammar :mod:`.alerts` rules and the
  ``GET /debug/query`` endpoint share.

Determinism is a feature, not an accident: under an injected ``now_fn``
(or explicit ``tick(now=...)``), identical sample streams produce
byte-identical query results — the seeded fuzz in
``tests/test_tsdb.py`` holds the module to that.

Divergences from PromQL, chosen for boundedness and determinism:
``increase()`` is the sum of positive deltas over points in the window
(reset-aware, no extrapolation), and ``rate()`` is that increase
divided by the window length.
"""

from __future__ import annotations

import json
import logging
import math
import re
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import (
    Callable,
    Deque,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
    Union,
)

from .core import (
    FAST_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    Registry,
    parse_exposition,
)

log = logging.getLogger(__name__)

# one (timestamp, value) sample
Point = Tuple[float, float]
# sorted (label, value) items — the hashable half of a series key
LabelItems = Tuple[Tuple[str, str], ...]
SeriesKey = Tuple[str, LabelItems]

# raw high-res window retained at tick resolution
DEFAULT_RAW_WINDOW_S = 300.0
# (bucket step, retention window) per downsampled tier, fine -> coarse;
# defaults follow the SRE burn-rate windows this TSDB exists to serve:
# 30s buckets cover the 1h window, 5m buckets the 6h window
DEFAULT_TIERS: Tuple[Tuple[float, float], ...] = (
    (30.0, 3600.0),
    (300.0, 21600.0),
)
# hard cap on retained series; past it new series are dropped and
# counted, never silently grown
DEFAULT_MAX_SERIES = 4096
# raw ring length in points (the second half of the raw bound: the
# window prunes by time, this prunes by count when ticks come fast)
DEFAULT_RAW_POINTS = 512
# instant-vector staleness: a series with no sample in this window
# before the evaluation time yields no value (mirrors Prometheus's
# 5m staleness default)
DEFAULT_LOOKBACK_S = 300.0

_DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h|d)?\s*$")
_DURATION_UNIT_S = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
                    "d": 86400.0, None: 1.0}


def parse_duration(text: str) -> float:
    """``"30s"``/``"5m"``/``"1h"``/``"250ms"``/bare seconds -> seconds."""
    m = _DURATION_RE.match(text)
    if not m:
        raise ValueError(f"bad duration {text!r} (want e.g. 30s, 5m, 1h)")
    return float(m.group(1)) * _DURATION_UNIT_S[m.group(2)]


def format_duration(seconds: float) -> str:
    """Inverse of :func:`parse_duration` for round-trippable display."""
    for unit, scale in (("d", 86400.0), ("h", 3600.0), ("m", 60.0)):
        if seconds >= scale and seconds % scale == 0:
            return f"{int(seconds / scale)}{unit}"
    if seconds == int(seconds):
        return f"{int(seconds)}s"
    return f"{seconds}s"


# -- expression grammar ------------------------------------------------------

_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SELECTOR_RE = re.compile(
    rf"^\s*({_NAME_RE})\s*(\{{[^}}]*\}})?\s*$")
_MATCHER_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(,|$)')
_RANGE_FN_RE = re.compile(
    rf"^\s*(rate|increase|avg_over_time|min_over_time|max_over_time)"
    rf"\s*\(\s*(.+?)\s*\[\s*([^\]]+)\s*\]\s*\)\s*$", re.S)
_HISTQ_RE = re.compile(
    r"^\s*histogram_quantile\s*\(\s*([0-9.]+)\s*,"
    r"\s*(.+?)\s*\[\s*([^\]]+)\s*\]\s*\)\s*$", re.S)

RANGE_FUNCTIONS = ("rate", "increase", "avg_over_time",
                   "min_over_time", "max_over_time",
                   "histogram_quantile")


@dataclass(frozen=True)
class Selector:
    """``name{label="value",...}`` — an instant vector selector."""

    name: str
    matchers: LabelItems = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.matchers)

    def __str__(self) -> str:
        if not self.matchers:
            return self.name
        body = ",".join(f'{k}="{v}"' for k, v in self.matchers)
        return f"{self.name}{{{body}}}"


@dataclass(frozen=True)
class RangeExpr:
    """``fn(selector[window])`` — a range function over one selector.

    ``histogram_quantile`` carries its quantile in ``quantile`` and
    selects the base histogram name (``_bucket`` resolved internally).
    """

    fn: str
    selector: Selector
    window_s: float
    quantile: Optional[float] = None

    def __str__(self) -> str:
        win = format_duration(self.window_s)
        if self.fn == "histogram_quantile":
            return (f"histogram_quantile({self.quantile}, "
                    f"{self.selector}[{win}])")
        return f"{self.fn}({self.selector}[{win}])"


Expr = Union[Selector, RangeExpr]


def _unescape(v: str) -> str:
    return v.replace('\\"', '"').replace("\\n", "\n").replace(
        "\\\\", "\\")


def parse_selector(text: str) -> Selector:
    m = _SELECTOR_RE.match(text)
    if not m:
        raise ValueError(f"bad selector {text!r}")
    name, raw = m.group(1), m.group(2)
    matchers: List[Tuple[str, str]] = []
    if raw:
        body = raw[1:-1].strip()
        pos = 0
        while pos < len(body):
            mm = _MATCHER_RE.match(body, pos)
            if not mm:
                raise ValueError(f"bad label matcher in {text!r}")
            matchers.append((mm.group(1), _unescape(mm.group(2))))
            pos = mm.end()
        if body and not matchers:
            raise ValueError(f"bad label matcher in {text!r}")
    return Selector(name, tuple(sorted(matchers)))


def parse_expr(text: str) -> Expr:
    """Parse one query expression.  Grammar::

        expr     := selector
                  | fn '(' selector '[' duration ']' ')'
                  | 'histogram_quantile' '(' q ',' selector '[' dur ']' ')'
        fn       := 'rate' | 'increase' | 'avg_over_time'
                  | 'min_over_time' | 'max_over_time'
        selector := name ( '{' label '=' '"' value '"' , ... '}' )?
    """
    m = _HISTQ_RE.match(text)
    if m:
        q = float(m.group(1))
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        return RangeExpr("histogram_quantile", parse_selector(m.group(2)),
                         parse_duration(m.group(3)), quantile=q)
    m = _RANGE_FN_RE.match(text)
    if m:
        return RangeExpr(m.group(1), parse_selector(m.group(2)),
                         parse_duration(m.group(3)))
    return parse_selector(text)


def expr_metric_names(text: str) -> List[str]:
    """Metric family names referenced by an expression — the hook the
    tpulint O2 rule and doc tables use.  Raises on a malformed
    expression (a rule that cannot parse can never evaluate)."""
    expr = parse_expr(text)
    sel = expr if isinstance(expr, Selector) else expr.selector
    return [sel.name]


# -- storage -----------------------------------------------------------------

class _Series:
    """One retained series: raw ring + downsampled tier rings."""

    __slots__ = ("raw", "tiers")

    def __init__(self, tiers: Sequence[Tuple[float, float]],
                 raw_points: int) -> None:
        self.raw: Deque[Point] = deque(maxlen=raw_points)
        self.tiers: List[Deque[Point]] = [
            deque(maxlen=int(window / step) + 2)
            for step, window in tiers]

    def n_points(self) -> int:
        return len(self.raw) + sum(len(t) for t in self.tiers)


class TSDB:
    """Bounded retention + recording rules over one Registry.

    ``tick()`` samples the registry (render -> parse -> append); call
    it manually with a fake ``now`` in tests, or :meth:`start` a
    background thread in production.  Registered tick hooks (the alert
    evaluator) run after each sample pass, inside the same tick — so
    "within two evaluation ticks" is a real bound, not a race.
    """

    def __init__(self, registry: Registry, *,
                 raw_window_s: float = DEFAULT_RAW_WINDOW_S,
                 tiers: Sequence[Tuple[float, float]] = DEFAULT_TIERS,
                 max_series: int = DEFAULT_MAX_SERIES,
                 raw_points: int = DEFAULT_RAW_POINTS,
                 lookback_s: float = DEFAULT_LOOKBACK_S,
                 now_fn: Optional[Callable[[], float]] = None,
                 self_metrics: bool = True) -> None:
        if raw_window_s <= 0:
            raise ValueError("raw_window_s must be > 0")
        if max_series < 1 or raw_points < 2:
            raise ValueError("max_series >= 1 and raw_points >= 2")
        tiers = tuple(sorted(((float(s), float(w)) for s, w in tiers)))
        for step, window in tiers:
            if step <= 0 or window < step:
                raise ValueError(
                    f"bad tier (step={step}, window={window})")
        self._registry = registry
        self._raw_window_s = float(raw_window_s)
        self._tiers = tiers
        self._max_series = int(max_series)
        self._raw_points = int(raw_points)
        self._lookback_s = float(lookback_s)
        self._now_fn: Callable[[], float] = now_fn or time.time
        self._lock = threading.RLock()
        self._series: Dict[SeriesKey, _Series] = {}
        self._hooks: List[Callable[[float], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_tick: Optional[float] = None
        self._c_ticks: Optional[Counter] = None
        self._c_dropped: Optional[Counter] = None
        self._g_series: Optional[Gauge] = None
        self._g_points: Optional[Gauge] = None
        self._h_tick: Optional[Histogram] = None
        if self_metrics:
            self._c_ticks = registry.counter(
                "tpu_tsdb_ticks_total",
                "Registry sampling ticks the in-process TSDB has run.")
            self._c_dropped = registry.counter(
                "tpu_tsdb_dropped_samples_total",
                "Samples dropped because the TSDB series cap was "
                "reached (new series past the fixed memory budget).")
            self._g_series = registry.gauge(
                "tpu_tsdb_series",
                "Series currently retained by the in-process TSDB.")
            self._g_points = registry.gauge(
                "tpu_tsdb_points",
                "Points currently retained across all TSDB rings "
                "(raw window plus downsampled tiers).")
            self._h_tick = registry.histogram(
                "tpu_tsdb_tick_duration_seconds",
                "Wall time of one TSDB sampling tick (render + parse "
                "+ append).", buckets=FAST_BUCKETS_S)

    # -- clock + lifecycle ---------------------------------------------------

    def now(self) -> float:
        return self._now_fn()

    @property
    def registry(self) -> Registry:
        return self._registry

    @property
    def lookback_s(self) -> float:
        return self._lookback_s

    def add_tick_hook(self, fn: Callable[[float], None]) -> None:
        """Run *fn(now)* after every sample pass (alert evaluation)."""
        with self._lock:
            self._hooks.append(fn)

    def start(self, interval_s: float = 5.0) -> None:
        """Start the background sampling thread (idempotent)."""
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            t = threading.Thread(
                target=self._run, args=(float(interval_s),),
                name="obs-tsdb", daemon=True)
            self._thread = t
        t.start()

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)

    def _run(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.tick()
            except Exception:
                # one bad tick degrades freshness, never the server
                log.exception("tsdb tick failed")

    # -- write path ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> int:
        """Sample the registry once; returns the sample count."""
        t = self._now_fn() if now is None else float(now)
        t0 = time.perf_counter()
        text = self._registry.render()
        samples = parse_exposition(text)
        dropped = 0
        with self._lock:
            if self._last_tick is not None and t < self._last_tick:
                t = self._last_tick  # clock went backwards: clamp
            self._last_tick = t
            for name, labels, value in samples:
                if value != value:  # NaN never aggregates
                    continue
                key: SeriesKey = (name, tuple(sorted(labels.items())))
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= self._max_series:
                        dropped += 1
                        continue
                    s = self._series[key] = _Series(
                        self._tiers, self._raw_points)
                self._append_locked(s, t, value)
            n_series = len(self._series)
            n_points = sum(s.n_points() for s in self._series.values())
            hooks = list(self._hooks)
        if self._c_ticks is not None:
            self._c_ticks.inc()
        if dropped and self._c_dropped is not None:
            self._c_dropped.inc(dropped)
        if self._g_series is not None:
            self._g_series.set(float(n_series))
        if self._g_points is not None:
            self._g_points.set(float(n_points))
        if self._h_tick is not None:
            self._h_tick.observe(time.perf_counter() - t0)
        for fn in hooks:
            try:
                fn(t)
            except Exception:
                log.exception("tsdb tick hook failed")
        return len(samples)

    def _append_locked(self, s: _Series, t: float, value: float) -> None:
        raw = s.raw
        if raw and t <= raw[-1][0]:
            # same-instant re-tick (fake clocks do this): latest wins
            raw[-1] = (t, value)
        else:
            raw.append((t, value))
        cutoff = t - self._raw_window_s
        while raw and raw[0][0] < cutoff:
            raw.popleft()
        for (step, window), ring in zip(self._tiers, s.tiers):
            bucket = math.floor(t / step)
            if ring and math.floor(ring[-1][0] / step) >= bucket:
                ring[-1] = (t, value)  # last sample per aligned bucket
            else:
                ring.append((t, value))
            wcut = t - window
            while ring and ring[0][0] < wcut:
                ring.popleft()

    # -- read path -----------------------------------------------------------

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def series_names(self) -> List[str]:
        """Sorted distinct metric names currently held — the incident
        bundler enumerates these to snapshot whole family sets
        (``tpu_serve_*`` and the firing rule's referenced families)
        without knowing every name up front."""
        with self._lock:
            return sorted({name for name, _ in self._series})

    def point_count(self) -> int:
        with self._lock:
            return sum(s.n_points() for s in self._series.values())

    def _matching_locked(self, sel: Selector
                         ) -> List[Tuple[LabelItems, _Series]]:
        out: List[Tuple[LabelItems, _Series]] = []
        for (name, items), s in self._series.items():
            if name != sel.name:
                continue
            if sel.matchers and not sel.matches(dict(items)):
                continue
            out.append((items, s))
        out.sort(key=lambda kv: kv[0])
        return out

    @staticmethod
    def _merged(s: _Series, start: float, end: float) -> List[Point]:
        """Merge tiers + raw into one ascending point list: raw where
        available, each coarser tier only for time older than every
        finer level it hands off to."""
        merged: List[Point] = list(s.raw)
        oldest = merged[0][0] if merged else math.inf
        for ring in s.tiers:  # fine -> coarse
            older = [p for p in ring if p[0] < oldest]
            if older:
                merged = older + merged
                oldest = older[0][0]
        return [p for p in merged if start <= p[0] <= end]

    def points(self, sel: Selector, start: float, end: float
               ) -> List[Tuple[Dict[str, str], List[Point]]]:
        """Raw merged points per matching series over [start, end]."""
        with self._lock:
            matches = self._matching_locked(sel)
            return [(dict(items), self._merged(s, start, end))
                    for items, s in matches]

    # -- evaluation ----------------------------------------------------------

    @staticmethod
    def _increase(points: Sequence[Point]) -> float:
        """Reset-aware increase: sum of positive deltas."""
        inc = 0.0
        for i in range(1, len(points)):
            d = points[i][1] - points[i - 1][1]
            if d > 0:
                inc += d
        return inc

    def _window_points(self, s: _Series, at: float, window_s: float
                       ) -> List[Point]:
        """Points in (at - window, at], plus one baseline point just
        before the window so increase() sees the counter's value at
        window start (within the staleness lookback)."""
        start = at - window_s
        pts = self._merged(s, start - self._lookback_s, at)
        inside = [p for p in pts if p[0] > start]
        baseline = [p for p in pts if p[0] <= start]
        if baseline:
            return [baseline[-1]] + inside
        return inside

    def evaluate(self, expr: Union[str, Expr],
                 at: Optional[float] = None
                 ) -> List[Tuple[Dict[str, str], float]]:
        """Instant evaluation: (labels, value) per output series."""
        e = parse_expr(expr) if isinstance(expr, str) else expr
        t = self.now() if at is None else float(at)
        with self._lock:
            if isinstance(e, Selector):
                out: List[Tuple[Dict[str, str], float]] = []
                for items, s in self._matching_locked(e):
                    pts = self._merged(s, t - self._lookback_s, t)
                    if pts:
                        out.append((dict(items), pts[-1][1]))
                return out
            if e.fn == "histogram_quantile":
                return self._hist_quantile_locked(e, t)
            out = []
            for items, s in self._matching_locked(e.selector):
                pts = self._window_points(s, t, e.window_s)
                val = self._apply_fn(e, pts)
                if val is not None:
                    out.append((dict(items), val))
            return out

    def _apply_fn(self, e: RangeExpr, pts: List[Point]
                  ) -> Optional[float]:
        if not pts:
            return None
        if e.fn == "increase":
            return self._increase(pts)
        if e.fn == "rate":
            return self._increase(pts) / e.window_s
        values = [v for _, v in pts]
        if e.fn == "avg_over_time":
            return sum(values) / len(values)
        if e.fn == "min_over_time":
            return min(values)
        if e.fn == "max_over_time":
            return max(values)
        raise ValueError(f"unknown function {e.fn!r}")

    def _hist_quantile_locked(self, e: RangeExpr, at: float
                              ) -> List[Tuple[Dict[str, str], float]]:
        """histogram_quantile(q, name[w]): per label group (minus
        ``le``), quantile of the bucket *increase* over the window —
        the same interpolation PromQL makes."""
        base = e.selector.name
        if base.endswith("_bucket"):
            base = base[:-len("_bucket")]
        bucket_sel = Selector(base + "_bucket", e.selector.matchers)
        groups: Dict[LabelItems, Dict[float, float]] = {}
        for items, s in self._matching_locked(bucket_sel):
            labels = dict(items)
            le_raw = labels.pop("le", None)
            if le_raw is None:
                continue
            le = math.inf if le_raw == "+Inf" else float(le_raw)
            gkey = tuple(sorted(labels.items()))
            pts = self._window_points(s, at, e.window_s)
            inc = self._increase(pts)
            by_le = groups.setdefault(gkey, {})
            by_le[le] = by_le.get(le, 0.0) + inc
        q = e.quantile if e.quantile is not None else 0.5
        out: List[Tuple[Dict[str, str], float]] = []
        for gkey in sorted(groups):
            val = _bucket_quantile(groups[gkey], q)
            if val == val:  # skip NaN (empty window)
                out.append((dict(gkey), val))
        return out

    # -- HTTP ----------------------------------------------------------------

    def query_range(self, expr: Union[str, Expr], start: float,
                    end: float, step_s: Optional[float] = None
                    ) -> List[Dict[str, object]]:
        """Series for ``GET /debug/query``: selectors return stored
        points verbatim; range functions evaluate on a step grid."""
        e = parse_expr(expr) if isinstance(expr, str) else expr
        if end < start:
            raise ValueError("range end before start")
        if isinstance(e, Selector):
            out: List[Dict[str, object]] = []
            for labels, pts in self.points(e, start, end):
                out.append({"name": e.name, "labels": labels,
                            "points": [[t, v] for t, v in pts]})
            return out
        step = float(step_s) if step_s else max(
            1.0, (end - start) / 120.0)
        if step <= 0:
            raise ValueError("step must be > 0")
        by_series: Dict[Tuple[Tuple[str, str], ...],
                        List[List[float]]] = {}
        t = start
        while t <= end + 1e-9:
            for labels, val in self.evaluate(e, at=t):
                key = tuple(sorted(labels.items()))
                by_series.setdefault(key, []).append([t, val])
            t += step
        name = str(e)
        return [{"name": name, "labels": dict(key), "points": pts}
                for key, pts in sorted(by_series.items())]

    def handle_query(self, params: Mapping[str, str]
                     ) -> Dict[str, object]:
        """``GET /debug/query?expr=&range=[&step=][&at=]`` -> JSON
        payload.  Raises ValueError on a malformed request (surfaces
        map that to a 400)."""
        expr_text = params.get("expr", "")
        if not expr_text:
            raise ValueError("missing expr parameter")
        e = parse_expr(expr_text)
        range_s = parse_duration(params.get("range", "300"))
        if range_s <= 0:
            raise ValueError("range must be > 0")
        step_s = (parse_duration(params["step"])
                  if params.get("step") else None)
        end = float(params["at"]) if params.get("at") else self.now()
        start = end - range_s
        series = self.query_range(e, start, end, step_s)
        return {
            "expr": expr_text,
            "start": start,
            "end": end,
            "range_s": range_s,
            "series": series,
        }

    def handle_query_json(self, params: Mapping[str, str]) -> str:
        return json.dumps(self.handle_query(params), sort_keys=True)


def _bucket_quantile(by_le: Dict[float, float], q: float) -> float:
    """Quantile from cumulative bucket increases (PromQL's linear
    interpolation — same math as :func:`core.histogram_quantile` but
    over increases, not lifetime counts)."""
    if not by_le or math.inf not in by_le:
        return math.nan
    total = by_le[math.inf]
    if total <= 0:
        return math.nan
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound in sorted(by_le):
        cum = by_le[bound]
        if cum >= target:
            if bound == math.inf:
                return prev_bound
            if cum == prev_cum:
                return bound
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = bound, cum
    return prev_bound
