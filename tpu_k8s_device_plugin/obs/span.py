"""Span: one timed operation that feeds a histogram and leaves a
structured, trace-tagged log line behind.

The repo's hot paths (serving requests, Allocate RPCs, pulse rounds)
need BOTH a latency distribution (the histogram a dashboard reads) and
a per-occurrence trace (the log line an operator greps when one
request misbehaves).  A Span is the single object that does both, so
the two can never disagree about what was measured:

    with span("tpu_plugin_allocate", histogram=m.allocate_seconds,
              labels={"resource": "tpu"}, logger=log):
        ...                       # outcome=ok on clean exit
                                  # outcome=error if the body raises

    sp = Span("tpu_serve_request", histogram=m.request_seconds,
              request_id=rid)     # long-lived: ends on the terminal
    ...                           # event, possibly on another thread
    sp.end(outcome="throttled")

Since PR 4 a span also carries a :class:`~.trace.TraceContext`
(``trace=``): the trace-id lands in the log line, in the histogram
bucket's OpenMetrics exemplar, and in the flight-recorder event
(``recorder=``), so one id stitches every surface a request touched.

If the histogram family declares an ``outcome`` label, the outcome is
recorded there; otherwise it only reaches the log line.  ``end()`` is
idempotent — exactly one observation and one log line per span, even
when a handler thread and the scheduler race to finish a request.

Slow-span escalation: spans construct at DEBUG, but a span whose
duration crosses ``slow_threshold_s`` logs at WARNING instead — a
pathological request must not vanish at default log levels.  The
default threshold is 5x the histogram's top finite bucket (anything
past the distribution's measurable range is by definition pathological
for that surface); pass ``slow_threshold_s=0`` to disable.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import TYPE_CHECKING, Dict, Iterator, Optional

from .core import Histogram, escape_label_value

if TYPE_CHECKING:  # typing only: no runtime import-order coupling
    from .recorder import FlightRecorder
    from .trace import TraceContext

_default_log = logging.getLogger(__name__)

# slow_threshold_s default: this multiple of the histogram's top finite
# bucket (observations past the top bucket are already off the
# distribution's scale; 5x that is unambiguously pathological)
SLOW_THRESHOLD_BUCKETS = 5.0


class Span:
    """One timed operation (see module docstring)."""

    __slots__ = ("name", "histogram", "request_id", "labels", "logger",
                 "level", "trace", "recorder", "slow_threshold_s",
                 "t0", "_lock", "_done", "_notes")

    def __init__(self, name: str,
                 histogram: Optional[Histogram] = None,
                 request_id: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None,
                 logger: Optional[logging.Logger] = None,
                 level: int = logging.DEBUG,
                 trace: Optional["TraceContext"] = None,
                 recorder: Optional["FlightRecorder"] = None,
                 slow_threshold_s: Optional[float] = None) -> None:
        self.name = name
        self.histogram = histogram
        self.request_id = request_id
        self.labels = dict(labels or {})
        self.logger = logger if logger is not None else _default_log
        self.level = level
        self.trace = trace
        self.recorder = recorder
        if slow_threshold_s is None and histogram is not None:
            slow_threshold_s = (SLOW_THRESHOLD_BUCKETS
                                * histogram.top_finite_bucket)
        self.slow_threshold_s = slow_threshold_s or 0.0
        self.t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._done = False
        self._notes: Dict[str, object] = {}

    def annotate(self, **kv: object) -> "Span":
        """Attach extra key=value pairs to the eventual log line."""
        self._notes.update(kv)
        return self

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0

    def end(self, outcome: str = "ok") -> float:
        """Finish the span: observe the histogram once, log once,
        record once.  Idempotent — later calls return the recorded
        duration without re-observing (terminal events can race across
        threads)."""
        with self._lock:
            if self._done:
                prior = self._notes.get("_duration", 0.0)
                return prior if isinstance(prior, float) else 0.0
            self._done = True
            dt = time.perf_counter() - self.t0
            self._notes["_duration"] = dt
        trace = self.trace
        hist = self.histogram
        if hist is not None:
            tid = trace.trace_id if trace is not None else None
            if hist.labelnames:
                kv = dict(self.labels)
                if "outcome" in hist.labelnames:
                    kv["outcome"] = outcome
                hist.labels(**kv).observe(dt, trace_id=tid)
            else:
                hist.observe(dt, trace_id=tid)
        if self.recorder is not None:
            self.recorder.record(
                self.name, trace=trace, duration_s=dt, outcome=outcome,
                **{k: v for k, v in {**self.labels,
                                     **self._notes}.items()
                   if not k.startswith("_")})
        # slow-span escalation: a duration past the threshold logs at
        # WARNING whatever the construction level — pathological
        # requests must surface at default log levels, trace-id included
        level = self.level
        if self.slow_threshold_s and dt >= self.slow_threshold_s:
            level = max(level, logging.WARNING)
        if self.logger.isEnabledFor(level):
            parts = [f"span={self.name}"]
            if self.request_id:
                parts.append(f"request_id={self.request_id}")
            if trace is not None:
                parts.append(f"trace_id={trace.trace_id}")
                parts.append(f"span_id={trace.span_id}")
                if trace.parent_id:
                    parts.append(f"parent_id={trace.parent_id}")
            parts.append(f"duration_s={dt:.6f}")
            parts.append(f"outcome={outcome}")
            if level >= logging.WARNING and self.slow_threshold_s:
                parts.append(
                    f"slow_threshold_s={self.slow_threshold_s:g}")
            for k in sorted(self.labels):
                parts.append(
                    f'{k}="{escape_label_value(self.labels[k])}"')
            for k in sorted(self._notes):
                if not k.startswith("_"):
                    parts.append(f"{k}={self._notes[k]}")
            self.logger.log(level, "%s", " ".join(parts))
        return dt


@contextmanager
def span(name: str,
         histogram: Optional[Histogram] = None,
         request_id: Optional[str] = None,
         labels: Optional[Dict[str, str]] = None,
         logger: Optional[logging.Logger] = None,
         level: int = logging.DEBUG,
         trace: Optional["TraceContext"] = None,
         recorder: Optional["FlightRecorder"] = None,
         slow_threshold_s: Optional[float] = None) -> Iterator[Span]:
    """Context-manager form: outcome=ok on clean exit, outcome=error
    (exception class name annotated) when the body raises."""
    sp = Span(name, histogram=histogram, request_id=request_id,
              labels=labels, logger=logger, level=level, trace=trace,
              recorder=recorder, slow_threshold_s=slow_threshold_s)
    try:
        yield sp
    except BaseException as e:
        sp.annotate(error=type(e).__name__).end(outcome="error")
        raise
    sp.end(outcome="ok")
