"""Span: one timed operation that feeds a histogram and leaves a
structured, request-id-tagged log line behind.

The repo's hot paths (serving requests, Allocate RPCs, pulse rounds)
need BOTH a latency distribution (the histogram a dashboard reads) and
a per-occurrence trace (the log line an operator greps when one
request misbehaves).  A Span is the single object that does both, so
the two can never disagree about what was measured:

    with span("tpu_plugin_allocate", histogram=m.allocate_seconds,
              labels={"resource": "tpu"}, logger=log):
        ...                       # outcome=ok on clean exit
                                  # outcome=error if the body raises

    sp = Span("tpu_serve_request", histogram=m.request_seconds,
              request_id=rid)     # long-lived: ends on the terminal
    ...                           # event, possibly on another thread
    sp.end(outcome="throttled")

If the histogram family declares an ``outcome`` label, the outcome is
recorded there; otherwise it only reaches the log line.  ``end()`` is
idempotent — exactly one observation and one log line per span, even
when a handler thread and the scheduler race to finish a request.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from .core import Histogram, escape_label_value

_default_log = logging.getLogger(__name__)


class Span:
    """One timed operation (see module docstring)."""

    __slots__ = ("name", "histogram", "request_id", "labels", "logger",
                 "level", "t0", "_lock", "_done", "_notes")

    def __init__(self, name: str,
                 histogram: Optional[Histogram] = None,
                 request_id: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None,
                 logger: Optional[logging.Logger] = None,
                 level: int = logging.DEBUG):
        self.name = name
        self.histogram = histogram
        self.request_id = request_id
        self.labels = dict(labels or {})
        self.logger = logger if logger is not None else _default_log
        self.level = level
        self.t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._done = False
        self._notes: Dict[str, object] = {}

    def annotate(self, **kv) -> "Span":
        """Attach extra key=value pairs to the eventual log line."""
        self._notes.update(kv)
        return self

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0

    def end(self, outcome: str = "ok") -> float:
        """Finish the span: observe the histogram once, log once.
        Idempotent — later calls return the recorded duration without
        re-observing (terminal events can race across threads)."""
        with self._lock:
            if self._done:
                return self._notes.get("_duration", 0.0)  # type: ignore
            self._done = True
            dt = time.perf_counter() - self.t0
            self._notes["_duration"] = dt
        hist = self.histogram
        if hist is not None:
            if hist.labelnames:
                kv = dict(self.labels)
                if "outcome" in hist.labelnames:
                    kv["outcome"] = outcome
                hist.labels(**kv).observe(dt)
            else:
                hist.observe(dt)
        if self.logger.isEnabledFor(self.level):
            parts = [f"span={self.name}"]
            if self.request_id:
                parts.append(f"request_id={self.request_id}")
            parts.append(f"duration_s={dt:.6f}")
            parts.append(f"outcome={outcome}")
            for k in sorted(self.labels):
                parts.append(
                    f'{k}="{escape_label_value(self.labels[k])}"')
            for k in sorted(self._notes):
                if not k.startswith("_"):
                    parts.append(f"{k}={self._notes[k]}")
            self.logger.log(self.level, "%s", " ".join(parts))
        return dt


@contextmanager
def span(name: str,
         histogram: Optional[Histogram] = None,
         request_id: Optional[str] = None,
         labels: Optional[Dict[str, str]] = None,
         logger: Optional[logging.Logger] = None,
         level: int = logging.DEBUG):
    """Context-manager form: outcome=ok on clean exit, outcome=error
    (exception class name annotated) when the body raises."""
    sp = Span(name, histogram=histogram, request_id=request_id,
              labels=labels, logger=logger, level=level)
    try:
        yield sp
    except BaseException as e:
        sp.annotate(error=type(e).__name__).end(outcome="error")
        raise
    sp.end(outcome="ok")
