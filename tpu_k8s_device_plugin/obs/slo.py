"""SLO policies + goodput accounting: the fleet's headline number.

ROADMAP item 5 replaces "tokens/sec" with **goodput** — requests/sec
meeting their class's SLO — because raw throughput hides exactly the
failure modes the QoS/preemption/router machinery exists for (a server
shedding every interactive request can still post a great tokens/sec).
This module is the accounting core both layers share:

- :class:`SLOPolicy` — one request class's objective: an interactive
  TTFT target (``ttft_ms``), a batch completion deadline
  (``deadline_ms``), or both, plus the error-budget objective the
  burn-rate gauge is computed against.
- :func:`parse_slo_specs` — the ``--slo CLASS=ttft_ms[:deadline_ms]``
  CLI grammar (repeatable).
- :class:`SLOAccountant` — wired into the serving request lifecycle:
  every terminal request increments
  ``tpu_slo_requests_total{class,tenant,met}`` and feeds a rolling
  window from which scrape-time gauges are refreshed —
  ``tpu_slo_goodput_ratio{class}`` (fraction meeting the SLO over the
  window), ``tpu_slo_goodput_requests_per_second{class}`` (met
  requests/sec over the window) and
  ``tpu_slo_error_budget_burn_rate{class}`` (observed miss rate over
  the budgeted miss rate; 1.0 = burning exactly the budget).

Label values are BOUNDED here, by construction: request-supplied class
names map to a declared policy or to ``other`` (never a free-form
label value), and tenant names map to the declared tenant set or to
``other`` — the O1 lint rule enforces that ``tpu_slo_*`` families are
only ever defined through this module so the bound cannot be bypassed.

All ``tpu_slo_*`` families are defined HERE and only here.  Stdlib
only, like the rest of ``obs``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from .core import Registry

# the label value every out-of-policy class or tenant collapses to:
# request bodies are attacker-controlled on the HTTP surface, and a
# free-form label value is a series-per-value memory leak
OTHER_LABEL = "other"

# tenant label value for requests that carry no tenant at all
DEFAULT_TENANT_LABEL = "default"

# fraction of requests that must meet their SLO before the error
# budget is burning faster than 1.0x
DEFAULT_OBJECTIVE = 0.99

# rolling window the goodput/burn-rate gauges are computed over
DEFAULT_WINDOW_S = 60.0


@dataclass(frozen=True)
class SLOPolicy:
    """One request class's SLO: a TTFT target and/or a completion
    deadline (at least one), plus the error-budget objective."""

    name: str
    ttft_ms: Optional[float] = None
    deadline_ms: Optional[float] = None
    objective: float = DEFAULT_OBJECTIVE

    def __post_init__(self) -> None:
        if self.ttft_ms is None and self.deadline_ms is None:
            raise ValueError(
                f"SLO class {self.name!r} needs a TTFT target and/or "
                "a completion deadline")
        if self.ttft_ms is not None and self.ttft_ms <= 0:
            raise ValueError(f"ttft_ms must be > 0 on {self.name!r}")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0 on {self.name!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1) on {self.name!r}")

    def met(self, ttft_s: Optional[float], total_s: float) -> bool:
        """Did a request with this first-token / total latency meet
        the class SLO?  A missing TTFT (no token ever streamed)
        fails a TTFT target by definition."""
        if self.ttft_ms is not None:
            if ttft_s is None or ttft_s * 1000.0 > self.ttft_ms:
                return False
        if self.deadline_ms is not None \
                and total_s * 1000.0 > self.deadline_ms:
            return False
        return True


def default_slo_policies() -> Dict[str, SLOPolicy]:
    """The policy set a server runs with when no ``--slo`` is given:
    ``interactive`` (TTFT target — streaming requests default here)
    and ``batch`` (completion deadline — unary requests default
    here).  Deliberately generous: defaults must classify, not shed."""
    return {
        "interactive": SLOPolicy("interactive", ttft_ms=2500.0),
        "batch": SLOPolicy("batch", deadline_ms=60000.0),
    }


def parse_slo_specs(specs: Optional[Iterable[str]]
                    ) -> Dict[str, SLOPolicy]:
    """``CLASS=ttft_ms[:deadline_ms]`` (repeatable) -> policy map.
    ``ttft_ms`` of 0 disables the TTFT target (deadline-only class:
    ``batch=0:60000``); a missing/0 deadline leaves TTFT-only."""
    out: Dict[str, SLOPolicy] = {}
    for spec in specs or ():
        name, _, rest = spec.partition("=")
        if not name or not rest:
            raise ValueError(
                f"bad --slo {spec!r} (want CLASS=ttft_ms[:deadline_ms])")
        parts = rest.split(":")
        if len(parts) > 2:
            raise ValueError(f"bad --slo {spec!r}")
        try:
            ttft = float(parts[0])
            deadline = float(parts[1]) if len(parts) > 1 else 0.0
        except ValueError:
            raise ValueError(
                f"bad --slo {spec!r}: targets must be numbers (ms)")
        out[name] = SLOPolicy(
            name,
            ttft_ms=ttft if ttft > 0 else None,
            deadline_ms=deadline if deadline > 0 else None)
    return out


class SLOAccountant:
    """Per-class SLO accounting over one registry (thread-safe).

    ``record()`` runs on the request's terminal path (scheduler or
    handler thread): one counter increment and one deque append.  The
    rolling-window gauges are refreshed lazily at scrape time through
    the registry's collect hook, so idle servers pay nothing."""

    def __init__(self, registry: Registry,
                 policies: Optional[Dict[str, SLOPolicy]] = None,
                 tenants: Iterable[str] = (),
                 window_s: float = DEFAULT_WINDOW_S) -> None:
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        self.policies: Dict[str, SLOPolicy] = dict(
            policies if policies is not None else default_slo_policies())
        if not self.policies:
            raise ValueError("need at least one SLO class")
        # the bounded tenant label set: declared quota tenants plus the
        # no-tenant default ("*" is the quota TEMPLATE, not a tenant)
        self._tenants = {t for t in tenants if t and t != "*"}
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        # per class: rolling (t_mono, met) window + lifetime totals
        self._events: Dict[str, Deque[Tuple[float, bool]]] = {
            name: deque() for name in self._label_classes()}
        self._totals: Dict[str, List[int]] = {
            name: [0, 0] for name in self._label_classes()}  # [total, met]
        reg = registry
        self._m_requests = reg.counter(
            "tpu_slo_requests_total",
            "Terminal requests by SLO class, tenant, and whether the "
            "class SLO was met (class/tenant values are bounded: "
            "unknown names map to 'other').",
            ("class", "tenant", "met"))
        self._g_goodput = reg.gauge(
            "tpu_slo_goodput_ratio",
            "Fraction of requests meeting their class SLO over the "
            "rolling window (1.0 when the window is empty).",
            ("class",))
        self._g_goodput_rps = reg.gauge(
            "tpu_slo_goodput_requests_per_second",
            "Requests per second meeting their class SLO over the "
            "rolling window — the fleet's goodput headline.",
            ("class",))
        self._g_burn = reg.gauge(
            "tpu_slo_error_budget_burn_rate",
            "Observed SLO miss rate over the budgeted miss rate "
            "(1 - objective) in the rolling window; 1.0 = burning "
            "exactly the budget, >1 = eating into it.",
            ("class",))
        # materialize every class's children so the families render
        # (as zeros / 1.0 goodput) from boot — dashboards and the
        # smoke promlint see one schema whether traffic arrived or not
        for name in self._label_classes():
            self._g_goodput.labels(**{"class": name}).set(1.0)
            self._g_goodput_rps.labels(**{"class": name}).set(0.0)
            self._g_burn.labels(**{"class": name}).set(0.0)
        reg.on_collect(self._collect)

    def _label_classes(self) -> List[str]:
        return list(self.policies) + [OTHER_LABEL]

    # -- label bounding ------------------------------------------------------

    def bound_class(self, slo_class: Optional[str]) -> str:
        """A request-supplied class name -> bounded label value."""
        if slo_class and slo_class in self.policies:
            return slo_class
        return OTHER_LABEL

    def bound_tenant(self, tenant: Optional[str]) -> str:
        """A request-supplied tenant -> bounded label value."""
        if not tenant:
            return DEFAULT_TENANT_LABEL
        return tenant if tenant in self._tenants else OTHER_LABEL

    # -- write path ----------------------------------------------------------

    def record(self, slo_class: Optional[str], tenant: Optional[str],
               *, ttft_s: Optional[float], total_s: float, ok: bool,
               fallback: str = "interactive") -> bool:
        """Account one terminal request.  *slo_class* is the (possibly
        free-form) request-supplied class; a request that declared no
        class lands under *fallback* (the server derives it from the
        request shape), and unknown non-empty names land under the
        ``other`` label, evaluated against *fallback*'s policy.
        Non-ok outcomes never meet an SLO.  Returns met."""
        label = self.bound_class(slo_class if slo_class else fallback)
        policy = self.policies.get(
            label if label != OTHER_LABEL else fallback)
        if policy is None:  # fallback not declared either: first policy
            policy = next(iter(self.policies.values()))
        met = ok and policy.met(ttft_s, total_s)
        self._m_requests.labels(**{
            "class": label, "tenant": self.bound_tenant(tenant),
            "met": "true" if met else "false"}).inc()
        now = time.monotonic()
        with self._lock:
            q = self._events[label]
            q.append((now, met))
            self._prune_locked(q, now)
            tot = self._totals[label]
            tot[0] += 1
            if met:
                tot[1] += 1
        return met

    def _prune_locked(self, q: Deque[Tuple[float, bool]],
                      now: float) -> None:
        cutoff = now - self.window_s
        while q and q[0][0] < cutoff:
            q.popleft()

    # -- read paths ----------------------------------------------------------

    def _window_counts(self, label: str) -> Tuple[int, int]:
        now = time.monotonic()
        with self._lock:
            q = self._events[label]
            self._prune_locked(q, now)
            total = len(q)
            met = sum(1 for _, m in q if m)
        return total, met

    def _collect(self) -> None:
        """Scrape-time gauge refresh (registry collect hook)."""
        for label in self._label_classes():
            total, met = self._window_counts(label)
            ratio = met / total if total else 1.0
            self._g_goodput.labels(**{"class": label}).set(ratio)
            self._g_goodput_rps.labels(**{"class": label}).set(
                met / self.window_s)
            policy = self.policies.get(label)
            budget = 1.0 - (policy.objective if policy is not None
                            else DEFAULT_OBJECTIVE)
            self._g_burn.labels(**{"class": label}).set(
                (1.0 - ratio) / budget if total else 0.0)

    def summary(self) -> Dict[str, object]:
        """The fixed-schema goodput block /statz (and through it the
        router's /fleet/statz and the future autoscaler) reads —
        cheap, flat, no Prometheus text on the polling hot path."""
        classes: Dict[str, Dict[str, object]] = {}
        for label in self._label_classes():
            total, met = self._window_counts(label)
            with self._lock:
                life_total, life_met = self._totals[label]
            policy = self.policies.get(label)
            budget = 1.0 - (policy.objective if policy is not None
                            else DEFAULT_OBJECTIVE)
            ratio = met / total if total else 1.0
            classes[label] = {
                "ttft_ms": policy.ttft_ms if policy else None,
                "deadline_ms": policy.deadline_ms if policy else None,
                "objective": policy.objective if policy
                else DEFAULT_OBJECTIVE,
                "total": life_total,
                "met": life_met,
                "window_total": total,
                "window_met": met,
                "goodput_ratio": ratio,
                "goodput_rps": met / self.window_s,
                "burn_rate": (1.0 - ratio) / budget if total else 0.0,
            }
        return {"window_s": self.window_s, "classes": classes}
