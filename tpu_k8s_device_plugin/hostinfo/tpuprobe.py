"""ctypes binding for libtpuprobe.so.

Importing this module loads (building on first use if a toolchain is
present) the native shim; ImportError signals "no native support" and
callers fall back to portable Python (e.g. the manager's stat-polling
kubelet watch, manager.py:_kubelet_watch_loop).
"""

from __future__ import annotations

import ctypes
import errno
import logging
import os
import shutil
import subprocess
import threading

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "libtpuprobe.so")
_SRC = os.path.normpath(
    os.path.join(_HERE, "..", "..", "native", "tpuprobe", "tpuprobe.cpp")
)
_build_lock = threading.Lock()


def _build() -> bool:
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if not cxx or not os.path.exists(_SRC):
        return False
    cmd = [
        cxx, "-O2", "-Wall", "-fPIC", "-fvisibility=hidden", "-std=c++17",
        "-shared", "-o", _SO_PATH, _SRC,
    ]
    try:
        # tpulint: disable=R1 -- one-shot g++ build at import with its own 120s timeout; failure logs and degrades to the portable sysfs parser, a retry would rebuild the same failure
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("tpuprobe build failed: %s", e)
        return False


def _stale() -> bool:
    """True when the shared object is missing or older than its source."""
    if not os.path.exists(_SO_PATH):
        return True
    try:
        return os.path.getmtime(_SRC) > os.path.getmtime(_SO_PATH)
    except OSError:
        return False


def _load() -> ctypes.CDLL:
    with _build_lock:
        if _stale() and not _build():
            # Never load a shim older than its source: the errno contract
            # (ENOTSUP sentinel, ESTALE watch death) is part of the ABI and
            # callers hard-code it.  ImportError degrades callers to their
            # portable Python fallbacks, which is strictly safer than
            # mismatched native semantics.
            raise ImportError(
                "libtpuprobe.so is stale (or missing) and cannot be rebuilt"
            )
    lib = ctypes.CDLL(_SO_PATH, use_errno=True)
    lib.tp_version.restype = ctypes.c_char_p
    lib.tp_watch_create.restype = ctypes.c_void_p
    lib.tp_watch_create.argtypes = [ctypes.c_char_p]
    lib.tp_watch_wait.restype = ctypes.c_int
    lib.tp_watch_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tp_watch_destroy.argtypes = [ctypes.c_void_p]
    lib.tp_probe_device.restype = ctypes.c_int
    lib.tp_probe_device.argtypes = [ctypes.c_char_p]
    lib.tp_numa_node.restype = ctypes.c_int
    lib.tp_numa_node.argtypes = [ctypes.c_char_p]
    return lib


_lib = _load()


def version() -> str:
    """Shim version banner (≈ hwloc GetVersions used at startup,
    cmd/k8s-device-plugin/main.go:40)."""
    return _lib.tp_version().decode()


def probe_device_node(path: str) -> int:
    """0 when *path* exists as a character device, -ENOTSUP when it exists
    but isn't one (fixture trees), else -errno.  Stat-only — it never
    open(2)s the single-open TPU chardev, so it cannot steal the chip from
    (or race the launch of) a workload."""
    return _lib.tp_probe_device(path.encode())


def numa_node(pci_sysfs_dir: str) -> int:
    """NUMA node of a PCI function (>= 0; unknown collapses to 0), -errno
    on read failure."""
    return _lib.tp_numa_node(pci_sysfs_dir.encode())


class DirWatcher:
    """inotify watch on a directory (the fsnotify analog the plugin
    manager uses for kubelet-socket create/remove detection)."""

    def __init__(self, directory: str):
        ctypes.set_errno(0)
        self._handle = _lib.tp_watch_create(directory.encode())
        if not self._handle:
            err = ctypes.get_errno()
            raise OSError(
                err,
                f"inotify watch failed for {directory}: {os.strerror(err)}",
            )

    def wait(self, timeout_s: float = 1.0) -> bool:
        """True when a filesystem event arrived before the timeout; raises
        OSError when the watch itself is broken (callers fall back to
        polling rather than spinning on a dead fd)."""
        if self._handle is None:
            raise ValueError("watcher is closed")
        rc = _lib.tp_watch_wait(self._handle, int(timeout_s * 1000))
        if rc < 0:
            if rc == -errno.EINTR:
                return False  # signal during poll: just a spurious wakeup
            raise OSError(-rc, f"inotify wait failed: {os.strerror(-rc)}")
        return rc > 0

    def close(self) -> None:
        if self._handle is not None:
            _lib.tp_watch_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> "DirWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception as e:
            # destructor during interpreter teardown: even the
            # accounting must be best-effort, but a live process gets
            # the DEBUG line + tpu_suppressed_errors_total{site}
            try:
                from tpu_k8s_device_plugin.resilience import suppressed
                suppressed("tpuprobe.dirwatcher_del", e, logger=log)
            # tpulint: disable=R2 -- interpreter teardown: the accounting import itself can fail mid-shutdown; a __del__ must never raise
            except Exception:
                pass
