"""Native host-interface bindings (the cgo-boundary analog).

``tpuprobe`` loads ``libtpuprobe.so`` (built from ``native/tpuprobe/``)
via ctypes — the same division the reference draws with its cgo blocks
(/root/reference/internal/pkg/amdgpu/amdgpu.go:21-27,
internal/pkg/hwloc/hwloc.go:21-24): Python/Go owns policy, the native
shim owns kernel interfaces.  Import of ``tpuprobe`` raises when the
library is missing and can't be built; callers treat that as "no native
support" and fall back to portable paths.
"""
