"""Unified resilience layer: retry, circuit breaker, watchdog, faults.

Every failure-prone boundary in the node agents and the serving stack
(kubelet Register, slice Join/Heartbeat, health List, the libtpu/sysfs
probe, the k8s API client, the serving scheduler step) runs through the
shared policies in :mod:`.policy` instead of ad-hoc ``for attempt in
range(3)`` loops, and every one of those boundaries carries a
deterministic fault-injection hook from :mod:`.faults` so the recovery
paths can be provoked on demand (the chaos harness in
``tools/chaos_soak.py``) instead of waiting for production to exercise
them.  See ``docs/user-guide/resilience.md``.
"""

from .faults import (
    FaultInjector,
    FaultSpec,
    InjectedFault,
    active,
    install,
    install_from_env,
    uninstall,
)
from .policy import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    CircuitOpenError,
    ResilienceMetrics,
    RetryPolicy,
    Watchdog,
    WatchdogTimeout,
    set_suppressed_metrics,
    suppressed,
)

__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CircuitBreaker",
    "CircuitOpenError",
    "FaultInjector",
    "FaultSpec",
    "InjectedFault",
    "ResilienceMetrics",
    "RetryPolicy",
    "Watchdog",
    "WatchdogTimeout",
    "active",
    "install",
    "install_from_env",
    "set_suppressed_metrics",
    "suppressed",
    "uninstall",
]
