"""Deterministic, seeded fault injection.

PR 4 gave the repo eyes (traces + flight recorder); this module gives it
a fist: every recovery path can be provoked on demand, repeatably, with
a one-line spec — no kernel modules, no tc/netem, no flaky sleeps.

Spec grammar (``--fault-spec`` / ``TPU_DP_FAULTS``)::

    spec  := rule (';' rule)*
    rule  := op ':' kind ':' arg [':' prob]
    op    := dotted operation name (kubelet.register, slice.join,
             slice.heartbeat, health.list, probe, serve.step,
             serve.schedule, ...)
    kind  := 'error' | 'drop' | 'hang'
    arg   := error/drop: probability in [0,1]
             hang: seconds to stall (optional prob as 4th field)

Examples::

    slice.join:error:0.3            # 30% of joins fail fast
    probe:hang:5                    # every probe stalls 5s
    kubelet.register:drop:0.5       # half the Registers are lost
    serve.step:error:0.02           # 2% of scheduler steps crash
    serve.schedule:hang:5           # every scheduler iteration wedges
                                    # 5s (trips the schedule watchdog)

Determinism: the injector owns one ``random.Random(seed)``; the same
seed and call sequence produce the same injections, so a chaos failure
reproduces with ``--seed N`` exactly like an engine fuzz failure
reproduces with ``ENGINE_FUZZ_SEED``.

Zero overhead when unset: injection is armed by assigning the module
global ``ACTIVE``.  Hot-path call sites are written as::

    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("serve.step")

so the disabled cost is one module-attribute load and an ``is None``
test — no function call, no dict lookup (a test asserts this shape).
``error`` and ``drop`` raise :class:`InjectedFault`; boundaries that
retry on transport errors list it in their retry/except tuples, which
keeps the injection visible to exactly the recovery machinery under
test and invisible to everything else.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import TYPE_CHECKING, Dict, List, Optional

if TYPE_CHECKING:  # typing only: the runtime stays stdlib-importable
    from tpu_k8s_device_plugin.obs import FlightRecorder

log = logging.getLogger(__name__)

ENV_FAULTS = "TPU_DP_FAULTS"
ENV_FAULT_SEED = "TPU_DP_FAULT_SEED"

_KINDS = ("error", "drop", "hang")


class InjectedFault(Exception):
    """A fault fired by the injector (never raised in production
    configs: constructing one requires an installed spec)."""

    def __init__(self, op: str, kind: str) -> None:
        super().__init__(f"injected {kind} at {op}")
        self.op = op
        self.kind = kind


class FaultRule:
    """One parsed spec rule."""

    __slots__ = ("op", "kind", "arg", "prob")

    def __init__(self, op: str, kind: str, arg: float,
                 prob: float) -> None:
        self.op = op
        self.kind = kind
        self.arg = arg
        self.prob = prob

    def __repr__(self) -> str:
        return (f"FaultRule({self.op}:{self.kind}:{self.arg:g}"
                f":{self.prob:g})")


class FaultSpec:
    """A parsed ``--fault-spec`` string (rules in declaration order)."""

    def __init__(self, rules: List[FaultRule], text: str = "") -> None:
        self.rules = rules
        self.text = text

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        rules: List[FaultRule] = []
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) not in (3, 4):
                raise ValueError(
                    f"bad fault rule {part!r}: want op:kind:arg[:prob]")
            op, kind = fields[0].strip(), fields[1].strip()
            if not op:
                raise ValueError(f"bad fault rule {part!r}: empty op")
            if kind not in _KINDS:
                raise ValueError(
                    f"bad fault rule {part!r}: kind must be one of "
                    f"{', '.join(_KINDS)}")
            try:
                arg = float(fields[2])
            except ValueError:
                raise ValueError(
                    f"bad fault rule {part!r}: arg must be a number")
            if kind == "hang":
                if arg < 0:
                    raise ValueError(
                        f"bad fault rule {part!r}: hang seconds < 0")
                prob = float(fields[3]) if len(fields) == 4 else 1.0
            else:
                if len(fields) == 4:
                    raise ValueError(
                        f"bad fault rule {part!r}: {kind} takes "
                        "probability as its arg, no 4th field")
                prob = arg
            if not 0.0 <= prob <= 1.0:
                raise ValueError(
                    f"bad fault rule {part!r}: probability {prob} "
                    "outside [0, 1]")
            rules.append(FaultRule(op, kind, arg, prob))
        return cls(rules, text)


class FaultInjector:
    """Seeded rule evaluator with per-op fire accounting.

    ``fire(op)`` walks the rules for *op* in declaration order: a
    ``hang`` rule that fires sleeps; an ``error``/``drop`` rule that
    fires raises :class:`InjectedFault` (ending the walk).  Fired
    injections are counted in ``fired`` and journaled to *recorder*
    so a chaos soak can assert exactly which faults landed.
    """

    def __init__(self, spec: FaultSpec, seed: int = 0,
                 recorder: Optional["FlightRecorder"] = None) -> None:
        self.spec = spec
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.recorder = recorder
        self.fired: Dict[str, int] = {}
        self.checked: Dict[str, int] = {}
        self._by_op: Dict[str, List[FaultRule]] = {}
        for r in spec.rules:
            self._by_op.setdefault(r.op, []).append(r)

    def _roll(self) -> float:
        with self._lock:  # one RNG stream, callers on many threads
            return self._rng.random()

    def _mark(self, d: Dict[str, int], key: str) -> None:
        with self._lock:
            d[key] = d.get(key, 0) + 1

    def fire(self, op: str) -> None:
        """Evaluate the rules for *op* (see class docstring)."""
        rules = self._by_op.get(op)
        self._mark(self.checked, op)
        if not rules:
            return
        for r in rules:
            if r.prob < 1.0 and self._roll() >= r.prob:
                continue
            self._mark(self.fired, f"{op}:{r.kind}")
            if self.recorder is not None:
                self.recorder.record("tpu_fault_injected", op=op,
                                     kind=r.kind, arg=r.arg)
            log.warning("fault injected: %s %s (arg=%g)",
                        op, r.kind, r.arg)
            if r.kind == "hang":
                time.sleep(r.arg)
            else:
                raise InjectedFault(op, r.kind)

    def fired_count(self, prefix: str = "") -> int:
        with self._lock:
            return sum(n for k, n in self.fired.items()
                       if k.startswith(prefix))


# The module-global arming switch.  None (the default, production
# state) makes every hook site a bare attribute check; tests and the
# chaos harness install/uninstall around each episode.
ACTIVE: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return ACTIVE


def install(spec_text: str, seed: int = 0,
            recorder: Optional["FlightRecorder"] = None
            ) -> Optional[FaultInjector]:
    """Parse and arm *spec_text*; empty/blank disarms.  Returns the
    installed injector (None when disarmed)."""
    global ACTIVE
    if not spec_text or not spec_text.strip():
        ACTIVE = None
        return None
    inj = FaultInjector(FaultSpec.parse(spec_text), seed=seed,
                        recorder=recorder)
    ACTIVE = inj
    log.warning("FAULT INJECTION ARMED (seed=%d): %s", seed,
                inj.spec.text)
    return inj


def uninstall() -> None:
    global ACTIVE
    ACTIVE = None


def install_from_env(recorder: Optional["FlightRecorder"] = None
                     ) -> Optional[FaultInjector]:
    """Arm from ``TPU_DP_FAULTS`` / ``TPU_DP_FAULT_SEED`` when set —
    the env path the DaemonSet and chaos subprocesses use."""
    spec = os.environ.get(ENV_FAULTS, "")
    if not spec:
        return None
    try:
        seed = int(os.environ.get(ENV_FAULT_SEED, "0"))
    except ValueError:
        log.error("bad %s; defaulting fault seed to 0", ENV_FAULT_SEED)
        seed = 0
    return install(spec, seed=seed, recorder=recorder)
