"""Shared retry / circuit-breaker / watchdog policies.

Before this module every failure-prone boundary rolled its own recovery:
``manager.py`` hardcoded 3x3s Register retries, ``slice/client.py``
carried a private backoff loop, ``health/client.py`` did a single-shot
RPC with no retry at all, and a hung libtpu/sysfs probe would stall the
whole pulse loop.  These three primitives replace all of that:

- :class:`RetryPolicy` — jittered exponential backoff with an attempt
  cap and an overall deadline.  The jitter RNG is seeded per policy so
  chaos runs replay byte-identically (the ``ENGINE_FUZZ_SEED``
  discipline applied to backoff).
- :class:`CircuitBreaker` — classic closed/open/half-open.  Open calls
  fail fast with :class:`CircuitOpenError`; after ``reset_timeout_s``
  ONE probe call is admitted (half-open) and its outcome decides
  whether the circuit closes again.
- :class:`Watchdog` — hung-call containment: the call runs on a worker
  thread and the caller gets :class:`WatchdogTimeout` after
  ``timeout_s`` instead of blocking forever.  The abandoned thread is
  left to die with its call (Python cannot kill it), which is exactly
  the trade the pulse loop needs: mark the probe failed NOW, let the
  wedged syscall rot in the background.

All three emit obs metrics when given a :class:`ResilienceMetrics`
(``tpu_resilience_retries_total{op}``, ``tpu_breaker_state{op}``,
``tpu_watchdog_trips_total{op}``) and journal state transitions to the
PR-4 flight recorder, so a chaos soak can assert not just that the
system reconverged but that the resilience layer is what did it.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import (
    TYPE_CHECKING,
    Callable,
    Dict,
    List,
    Optional,
    Tuple,
    Type,
    TypeVar,
)

if TYPE_CHECKING:  # typing only: the runtime stays stdlib-importable
    from tpu_k8s_device_plugin.obs import FlightRecorder, Registry

log = logging.getLogger(__name__)

_T = TypeVar("_T")

# tpu_breaker_state{op} gauge values (documented in the metric help
# text and docs/user-guide/resilience.md)
BREAKER_CLOSED = 0
BREAKER_OPEN = 1
BREAKER_HALF_OPEN = 2

_STATE_NAMES: Dict[int, str] = {
    BREAKER_CLOSED: "closed", BREAKER_OPEN: "open",
    BREAKER_HALF_OPEN: "half_open"}


class CircuitOpenError(RuntimeError):
    """Raised by a breaker that is refusing calls (fail-fast)."""


class WatchdogTimeout(TimeoutError):
    """The watched call exceeded its deadline and was abandoned."""


class ResilienceMetrics:
    """The resilience metric families on one obs.Registry.

    Get-or-create semantics (the registry's own) mean every policy in a
    process shares one set of families; the ``op`` label tells the
    boundaries apart.  Also carries the suppressed-errors counter the
    once-silent ``except Exception: pass`` sites now increment.
    """

    def __init__(self, registry: "Registry") -> None:
        self.retries = registry.counter(
            "tpu_resilience_retries_total",
            "Retried attempts (attempt 2 and later) per operation.",
            ("op",))
        self.giveups = registry.counter(
            "tpu_resilience_giveups_total",
            "Retry loops that exhausted attempts/deadline, per "
            "operation.", ("op",))
        self.breaker_state = registry.gauge(
            "tpu_breaker_state",
            "Circuit-breaker state per operation: 0 closed, 1 open, "
            "2 half-open.", ("op",))
        self.breaker_transitions = registry.counter(
            "tpu_breaker_transitions_total",
            "Circuit-breaker state transitions per operation.",
            ("op", "to"))
        self.watchdog_trips = registry.counter(
            "tpu_watchdog_trips_total",
            "Calls abandoned by the watchdog after exceeding their "
            "deadline, per operation.", ("op",))
        self.suppressed = registry.counter(
            "tpu_suppressed_errors_total",
            "Exceptions swallowed at deliberately-forgiving sites "
            "(logged at DEBUG), by site.", ("site",))


_SUPPRESSED_METRICS: Optional[ResilienceMetrics] = None


def set_suppressed_metrics(metrics: Optional[ResilienceMetrics]) -> None:
    """Process-wide sink for :func:`suppressed` counts.  The cmd wiring
    points this at the node registry's families; library embedders that
    never call it still get the DEBUG log line."""
    global _SUPPRESSED_METRICS
    _SUPPRESSED_METRICS = metrics


def suppressed(site: str, exc: BaseException,
               logger: Optional[logging.Logger] = None,
               metrics: Optional[ResilienceMetrics] = None) -> None:
    """Account for a deliberately-swallowed exception.

    The contract for every ``except Exception: pass`` site that
    survives review: the fault stays non-fatal, but it is logged at
    DEBUG with the exception and counted in
    ``tpu_suppressed_errors_total{site}`` so a flood of swallowed
    faults is visible on /metrics instead of invisible forever.
    *metrics* pins the counter to a specific registry; without it the
    process-wide sink (see :func:`set_suppressed_metrics`) is used."""
    (logger or log).debug("suppressed error at %s: %s: %s",
                          site, type(exc).__name__, exc)
    m = metrics if metrics is not None else _SUPPRESSED_METRICS
    if m is not None:
        m.suppressed.labels(site=site).inc()


class RetryPolicy:
    """Jittered exponential backoff with attempt + deadline caps.

    ``call()`` runs *fn* until it succeeds, raises a non-retryable
    exception, exhausts ``max_attempts``, or crosses ``deadline_s``
    (measured from the first attempt).  ``sleeps()`` exposes the raw
    backoff schedule for callers that need to own their own loop (the
    slice client's join poll, which retries on a *response*, not an
    exception).
    """

    def __init__(self,
                 max_attempts: int = 3,
                 initial_backoff_s: float = 0.5,
                 max_backoff_s: float = 15.0,
                 multiplier: float = 2.0,
                 jitter: float = 0.1,
                 deadline_s: float = 0.0,
                 seed: Optional[int] = None) -> None:
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.max_attempts = max_attempts
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline_s = deadline_s
        # seeded per policy: a chaos run with a fixed seed replays the
        # same backoff schedule every time
        self._rng = random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before attempt *attempt*+1 (attempt is 1-based)."""
        base = min(self.initial_backoff_s
                   * (self.multiplier ** (attempt - 1)),
                   self.max_backoff_s)
        if self.jitter:
            base *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, base)

    def call(self, fn: Callable[[], _T], *, op: str,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             stop: Optional[threading.Event] = None,
             metrics: Optional[ResilienceMetrics] = None,
             recorder: Optional["FlightRecorder"] = None,
             logger: Optional[logging.Logger] = None) -> _T:
        """Run *fn* under this policy.  Exceptions outside *retry_on*
        propagate immediately; the final retryable failure propagates
        after the budget is spent.  *stop* aborts the backoff sleep
        early (a stopping manager must not serve out a retry loop);
        an abort raises the last failure."""
        lg = logger or log
        t0 = time.monotonic()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            if stop is not None and stop.is_set():
                break
            try:
                return fn()
            except retry_on as e:
                last = e
                out_of_time = (
                    self.deadline_s
                    and time.monotonic() - t0 >= self.deadline_s)
                if attempt >= self.max_attempts or out_of_time:
                    break
                delay = self.backoff_s(attempt)
                lg.warning("%s attempt %d/%d failed (%s); retrying in "
                           "%.2fs", op, attempt, self.max_attempts,
                           e, delay)
                if metrics is not None:
                    metrics.retries.labels(op=op).inc()
                if recorder is not None:
                    recorder.record("tpu_resilience_retry", op=op,
                                    attempt=attempt, error=str(e))
                if stop is not None:
                    if stop.wait(delay):
                        break
                else:
                    time.sleep(delay)
        if metrics is not None:
            metrics.giveups.labels(op=op).inc()
        if recorder is not None:
            recorder.record("tpu_resilience_giveup", op=op,
                            error=str(last))
        if last is None:
            raise CircuitOpenError(f"{op}: aborted by stop event "
                                   "before the first attempt")
        raise last


class CircuitBreaker:
    """Closed/open/half-open breaker with single-probe admission.

    ``allow()`` answers whether a call may proceed; callers then report
    the outcome via ``record_success()`` / ``record_failure()`` — or
    use ``call()`` which does all three.  ``failure_threshold``
    consecutive failures open the circuit; after ``reset_timeout_s``
    exactly one caller wins the half-open probe slot and its outcome
    closes or re-opens the circuit.  Thread-safe.
    """

    def __init__(self, op: str,
                 failure_threshold: int = 3,
                 reset_timeout_s: float = 30.0,
                 metrics: Optional[ResilienceMetrics] = None,
                 recorder: Optional["FlightRecorder"] = None,
                 logger: Optional[logging.Logger] = None) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.op = op
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._metrics = metrics
        self._recorder = recorder
        self._log = logger or log
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        if metrics is not None:
            metrics.breaker_state.labels(op=op).set(BREAKER_CLOSED)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def _transition(self, to: int) -> None:
        # lock held by caller
        if self._state == to:
            return
        self._state = to
        name = _STATE_NAMES[to]
        self._log.log(
            logging.WARNING if to != BREAKER_CLOSED else logging.INFO,
            "breaker %s -> %s", self.op, name)
        if self._metrics is not None:
            self._metrics.breaker_state.labels(op=self.op).set(to)
            self._metrics.breaker_transitions.labels(
                op=self.op, to=name).inc()
        if self._recorder is not None:
            self._recorder.record("tpu_breaker_transition", op=self.op,
                                  to=name)

    def allow(self) -> bool:
        """May a call proceed right now?  In half-open, only the first
        caller after the reset timeout gets True (the probe)."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if (time.monotonic() - self._opened_at
                    >= self.reset_timeout_s):
                if self._probe_inflight:
                    return False
                self._transition(BREAKER_HALF_OPEN)
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            self._transition(BREAKER_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probe_inflight = False
            if self._state == BREAKER_HALF_OPEN \
                    or self._failures >= self.failure_threshold:
                self._opened_at = time.monotonic()
                self._transition(BREAKER_OPEN)

    def call(self, fn: Callable[[], _T]) -> _T:
        """Run *fn* through the breaker: :class:`CircuitOpenError`
        when open, outcome recorded otherwise.  BaseExceptions
        (KeyboardInterrupt) pass through without counting."""
        if not self.allow():
            raise CircuitOpenError(
                f"{self.op}: circuit open "
                f"({self._failures} consecutive failures)")
        try:
            result = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


class Watchdog:
    """Fail a hung call instead of blocking its thread.

    ``call()`` runs *fn* on a fresh daemon worker thread and waits at
    most ``timeout_s``: on time, the result (or exception) is
    propagated; past it, :class:`WatchdogTimeout` is raised and the
    worker is ABANDONED — it finishes (or hangs) in the background and
    its eventual result is discarded.  That leak-a-thread trade is
    deliberate and bounded by the caller's call rate; it is the only
    containment Python offers for a call wedged inside a C extension
    (libtpu, a dead-NFS stat), and it is what keeps one wedged probe
    from freezing the whole pulse loop.
    """

    def __init__(self, op: str, timeout_s: float,
                 metrics: Optional[ResilienceMetrics] = None,
                 recorder: Optional["FlightRecorder"] = None,
                 logger: Optional[logging.Logger] = None) -> None:
        if timeout_s <= 0:
            raise ValueError("watchdog timeout must be > 0")
        self.op = op
        self.timeout_s = timeout_s
        self._metrics = metrics
        self._recorder = recorder
        self._log = logger or log

    def call(self, fn: Callable[[], _T]) -> _T:
        results: List[_T] = []
        errors: List[BaseException] = []
        done = threading.Event()

        def run() -> None:
            try:
                results.append(fn())
            # tpulint: disable=R2 -- not a swallow: the exception is re-raised to the waiter below
            except BaseException as e:
                errors.append(e)
            finally:
                done.set()

        t = threading.Thread(target=run,
                             name=f"watchdog-{self.op}", daemon=True)
        t.start()
        if not done.wait(self.timeout_s):
            self._log.warning(
                "watchdog: %s exceeded %.1fs; abandoning the call",
                self.op, self.timeout_s)
            if self._metrics is not None:
                self._metrics.watchdog_trips.labels(op=self.op).inc()
            if self._recorder is not None:
                self._recorder.record("tpu_watchdog_trip", op=self.op,
                                      timeout_s=self.timeout_s)
            raise WatchdogTimeout(
                f"{self.op} exceeded {self.timeout_s:.1f}s watchdog")
        if errors:
            raise errors[0]
        return results[0]
