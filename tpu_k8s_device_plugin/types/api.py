"""DeviceImpl: the contract between the plugin adapter and device implementations.

Mirrors the seven-method interface of the reference
(/root/reference/internal/pkg/types/api.go:25-47) and its per-resource plugin
context (api.go:49-56).  Each kubelet RPC on the plugin adapter delegates to
exactly one DeviceImpl method; a single DeviceImpl instance may back several
resource names (mixed naming strategy), distinguished via the context.
"""

from __future__ import annotations

import abc
from typing import List, Optional, TYPE_CHECKING

if TYPE_CHECKING:  # only for type hints; avoids a hard import cycle
    from tpu_k8s_device_plugin.allocator.allocator import Policy
    from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi


class DevicePluginContext:
    """Per-resource state handed to every DeviceImpl call.

    Reference: DevicePluginContext interface (api.go:49-56).  Holds the resource
    name this plugin instance serves, the preferred-allocation policy, and a
    sticky flag recording that allocator initialisation failed (in which case
    GetPreferredAllocation degrades to kubelet-default allocation, the graceful
    degradation of reference amdgpu.go:111-117).
    """

    def __init__(self, resource_name: str, allocator: Optional["Policy"] = None):
        self._resource_name = resource_name
        self._allocator = allocator
        self._allocator_error = False

    def resource_name(self) -> str:
        return self._resource_name

    def get_allocator(self) -> Optional["Policy"]:
        return self._allocator

    def set_allocator_error(self, err: bool) -> None:
        self._allocator_error = err

    def get_allocator_error(self) -> bool:
        return self._allocator_error


class DeviceImpl(abc.ABC):
    """Device implementation interface (reference api.go:25-47).

    Implementations: TpuKfdStyleImpl (container workloads via /dev/accel),
    TpuVfImpl (VM passthrough via VFIO VFs), TpuPfImpl (PF passthrough).
    """

    @abc.abstractmethod
    def start(self, ctx: DevicePluginContext) -> None:
        """Called after plugin init and before registration with the kubelet."""

    @abc.abstractmethod
    def get_resource_names(self) -> List[str]:
        """Resource names (without namespace) this impl advertises."""

    @abc.abstractmethod
    def get_options(self, ctx: DevicePluginContext) -> "pluginapi.DevicePluginOptions":
        """Device plugin options for the resource."""

    @abc.abstractmethod
    def enumerate(self, ctx: DevicePluginContext) -> List["pluginapi.Device"]:
        """List of devices for the resource, with NUMA topology hints."""

    @abc.abstractmethod
    def allocate(
        self, ctx: DevicePluginContext, req: "pluginapi.AllocateRequest"
    ) -> "pluginapi.AllocateResponse":
        """Allocation artifacts (device nodes, mounts, env) for a request."""

    @abc.abstractmethod
    def get_preferred_allocation(
        self, ctx: DevicePluginContext, req: "pluginapi.PreferredAllocationRequest"
    ) -> "pluginapi.PreferredAllocationResponse":
        """Topology-preferred device subset for an admission-time request."""

    @abc.abstractmethod
    def update_health(self, ctx: DevicePluginContext) -> List["pluginapi.Device"]:
        """Re-probed device list with current Healthy/Unhealthy states."""

    def rediscover(self) -> bool:
        """Re-enumerate the hardware; True when the advertised device or
        resource set changed (the manager then re-diffs resources and
        re-inits allocators — the runtime analog of the reference dpm's
        ResUpdateChan, vendor/.../dpm/manager.go:96-137, which the
        reference only ever feeds once at startup).  Default: static
        hardware, nothing to do."""
        return False
