"""Contract layer: the DeviceImpl interface and TPU constants.

TPU-native analog of the reference's ``internal/pkg/types``
(/root/reference/internal/pkg/types/api.go:25-56, constants.go:21-93).
"""

from .api import DeviceImpl, DevicePluginContext
from . import constants

__all__ = ["DeviceImpl", "DevicePluginContext", "constants"]
