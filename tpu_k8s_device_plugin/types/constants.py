"""TPU constants: resource names, driver types, sysfs/devfs paths, flags.

TPU-native analog of /root/reference/internal/pkg/types/constants.go:21-93.
Where the reference keys off the AMD vendor id / KFD / GIM driver paths, this
build keys off the Google vendor id, the Linux ``accel`` class that the TPU
driver registers chips under, and VFIO for VM passthrough.
"""

# ---------------------------------------------------------------------------
# Node labels the labeller can emit (flag-gated, one bool flag per entry).
# Reference: SupportedLabels, constants.go:21.
# ---------------------------------------------------------------------------
SUPPORTED_LABELS = [
    "mode",                          # container / vf-passthrough / pf-passthrough
    "accelerator-type",              # e.g. v5litepod-8
    "topology",                      # ICI mesh, e.g. 2x4 or 2x2x1
    "chips-per-host",                # local chip count
    "cores-per-chip",                # TensorCores per chip (1 on v5e, 2 on v4/v5p)
    "worker-id",                     # this host's index within a multi-host slice
    "num-workers",                   # hosts in the slice
    "firmware",                      # TPU firmware version
    "driver-version",                # accel/TPU kernel driver version
    "device-id",                     # PCI device id of the chips
    "product-name",                  # marketing name, e.g. "TPU v5e"
    "hbm",                           # HBM bytes per chip
    "partitioning-supported",        # whether per-core partitioning is available
    "core-partition",                # current partition granularity (chip / core)
    "slice-id",                      # formed-slice identity hash (pod affinity key)
    "slice-rank",                    # this host's rendezvous-assigned rank
    "slice-generation",              # membership generation (bumps on reshape)
    "slice-workers",                 # hosts in the CURRENT generation (shrinks on reshape)
    "slice-degraded",                # "true" when reshaped below the configured size
]

# Label prefixes.  The reference emits both amd.com/gpu.* and a legacy
# beta.amd.com/gpu.* prefix (cmd/k8s-node-labeller/main.go:85-116); we mirror
# that with google.com/tpu.* plus a legacy beta prefix.
LABEL_PREFIX = "google.com/tpu"
LABEL_PREFIX_BETA = "beta.google.com/tpu"

# ---------------------------------------------------------------------------
# Command-line parameter names (constants.go:24-33).
# ---------------------------------------------------------------------------
CMDLINE_PULSE = "pulse"
CMDLINE_DRIVER_TYPE = "driver_type"
CMDLINE_RES_NAMING_STRATEGY = "resource_naming_strategy"
CMDLINE_SLICE_RENDEZVOUS = "slice_rendezvous"
CMDLINE_SLICE_WORKERS = "slice_workers"

# Resource naming strategies (constants.go:36-42).
RESOURCE_NAMING_STRATEGY_SINGLE = "single"
RESOURCE_NAMING_STRATEGY_MIXED = "mixed"

# Driver types (constants.go:45-54).
CONTAINER = "container"
VF_PASSTHROUGH = "vf-passthrough"
PF_PASSTHROUGH = "pf-passthrough"

# ---------------------------------------------------------------------------
# TPU hardware constants (≈ AMDGPU constants, constants.go:57-93).
# ---------------------------------------------------------------------------

# Google PCI vendor id (reference uses AMD 0x1002, constants.go:80).
GOOGLE_VENDOR_ID = "0x1ae0"

# Known TPU PCI device ids → generation (probed from config space; used by
# discovery fallback and the labeller's device-id/product-name generators).
TPU_PCI_DEVICE_IDS = {
    "0x0027": "v2/v3",
    "0x005e": "v4",
    "0x0062": "v5e",
    "0x0063": "v5p",
    "0x006f": "v6e",
}

# Linux accel class: one entry per chip, accel/accel%d, with device/ symlink
# into the PCI device (the TPU analog of /sys/module/amdgpu/drivers/pci:amdgpu).
ACCEL_CLASS_PATH = "/sys/class/accel"

# Character device nodes the container path mounts (≈ /dev/kfd + /dev/dri/*).
ACCEL_DEV_DIR = "/dev/accel"          # /dev/accel0, /dev/accel1, ...
VFIO_DEV_DIR = "/dev/vfio"            # /dev/vfio/<iommu-group> + /dev/vfio/vfio

# PCI scan root for VF/PF passthrough discovery (constants.go:74).
PCI_DEVICE_PATH = "/sys/bus/pci/devices/"

# VFIO driver paths (constants.go:59-62).
VFIO_DRIVER_PATH = "/sys/bus/pci/drivers/vfio-pci"
VFIO_DRIVER_NAME = "vfio-pci"

# TPU VF driver (SR-IOV host driver for TPU VMs; ≈ AMD's gim driver,
# constants.go:65-71).
TPU_VF_DRIVER_PATH = "/sys/bus/pci/drivers/tpu-vf"
TPU_VF_MODULE_PATH = "/sys/module/tpu_vf"
TPU_VF_DRIVER_NAME = "tpu-vf"

# Env var prefix announcing allocated passthrough PCI addresses to the
# virt-launcher (≈ PCI_RESOURCE_AMD_COM, constants.go:77).
PCI_TPU_PREFIX = "PCI_RESOURCE_GOOGLE_COM"

# Resource namespace + device types reported to the kubelet
# (≈ amd.com / gpu / gpu_vf / gpu_pf, constants.go:83-89).
RESOURCE_NAMESPACE = "google.com"
DEVICE_TYPE_TPU = "tpu"
DEVICE_TYPE_TPU_VF = "tpu_vf"
DEVICE_TYPE_TPU_PF = "tpu_pf"

# Per-core partition resource name (mixed strategy on 2-core chips; the TPU
# analog of MI300 partition-typed resources like cpx_nps1).
DEVICE_TYPE_TPU_CORE = "tpucore"

# Per-chip health attributes the TPU driver exposes in the chip's PCI sysfs
# directory (the granular state an open(2) probe cannot see — a wedged chip
# whose chardev still opens).  Modelled in the synthesized fixture trees
# (testdata/make_fixtures.py); both files are optional on real hosts — a
# missing attribute contributes no verdict.
SYSFS_CHIP_STATE = "chip_state"             # "alive" when operational
CHIP_STATE_ALIVE = "alive"
SYSFS_UE_COUNT = "uncorrectable_errors"     # fatal (uncorrectable) error count

# Exporter health check timeout, seconds (constants.go:92).
EXPORTER_HEALTH_CHECK_TIMEOUT_S = 10.0

# Watchdog deadline for one whole granular health probe (PR 5): a probe
# wedged inside a C call past this is abandoned and the impl demotes
# every device until a probe succeeds again.  Must exceed
# EXPORTER_HEALTH_CHECK_TIMEOUT_S (a slow-but-bounded RPC is the
# exporter's problem, not a hang).
PROBE_WATCHDOG_TIMEOUT_S = 15.0

# Unix socket of the companion tpu-metrics-exporter daemon
# (≈ /var/lib/amd-metrics-exporter/..., exporter/health.go:35-37).
METRICS_EXPORTER_SOCKET = (
    "/var/lib/tpu-metrics-exporter/tpu_device_metrics_exporter_grpc.socket"
)

# TCP port of the exporter's Prometheus /metrics endpoint (the AMD
# analog is a metrics exporter first; the health gRPC is one service on
# it).  0 disables the HTTP listener.
METRICS_HTTP_PORT = 9400

# ---------------------------------------------------------------------------
# Kubelet device-plugin API surface (vendored constants in the reference:
# k8s.io/kubelet/pkg/apis/deviceplugin/v1beta1/constants.go).
# ---------------------------------------------------------------------------
KUBELET_DP_VERSION = "v1beta1"
DEVICE_PLUGIN_PATH = "/var/lib/kubelet/device-plugins/"
KUBELET_SOCKET = DEVICE_PLUGIN_PATH + "kubelet.sock"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

# ---------------------------------------------------------------------------
# TPU runtime environment: how allocated chips are announced to the workload
# (libtpu reads these; the analog of exposing only selected /dev/dri nodes).
# ---------------------------------------------------------------------------
ENV_TPU_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"
ENV_TPU_CHIPS_PER_HOST_BOUNDS = "TPU_CHIPS_PER_HOST_BOUNDS"
ENV_TPU_PROCESS_BOUNDS = "TPU_PROCESS_BOUNDS"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_SKIP_MDS_QUERY = "TPU_SKIP_MDS_QUERY"
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
# Slice membership env (set on full-host grants when slice coordination is
# on; the hostnames/worker-id pair mirrors what the Cloud TPU VM runtime
# publishes, the JAX triple feeds jax.distributed.initialize directly —
# see workloads/bench_main._maybe_init_distributed).
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_JAX_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_JAX_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_JAX_PROCESS_ID = "JAX_PROCESS_ID"
# Membership generation the identity above belongs to: workloads compare
# it against the live membership file (workloads.checkpoint.ReshapeSignal)
# to detect that the slice reshaped under them and a checkpoint-restart
# is due.
ENV_TPU_SLICE_GENERATION = "TPU_SLICE_GENERATION"

# Host-local metadata file written by the TPU VM runtime / GKE (fixture-able
# stand-in for the GCE metadata server's tpu-env attribute).
TPU_ENV_FILE = "/run/tpu/tpu-env"

# ---------------------------------------------------------------------------
# Multi-host slice coordination (slice/: rendezvous, ranks, slice health).
# ---------------------------------------------------------------------------

# Rendezvous gRPC port (the coordinator member's device plugin serves it);
# distinct from the JAX coordination port handed to workloads.
SLICE_RENDEZVOUS_PORT = 8475

# Port baked into the emitted JAX_COORDINATOR_ADDRESS (rank-0 host); same
# port example/multihost/jobset.yaml exposes on its headless Service.
SLICE_JAX_COORDINATOR_PORT = 8476

# Crash-safe membership file: the coordinator persists the formed slice
# here, every client mirrors what it learned, and the node labeller reads
# it for the slice-id/slice-rank labels.  Survives plugin restarts on the
# host path mount.
SLICE_STATE_FILE = "/var/lib/tpu-slice/membership.json"

# Heartbeat cadence (client) and staleness cutoff (coordinator): a member
# silent past the timeout drags the whole slice Unhealthy.
SLICE_HEARTBEAT_PERIOD_S = 5.0
SLICE_HEARTBEAT_TIMEOUT_S = 30.0

# Degraded-mode reshape grace window, seconds.  0 (the default) disables
# reshaping entirely: an unhealthy member demotes the whole slice until
# it recovers, exactly the pre-reshape behavior.  > 0: once the slice
# verdict flips unhealthy, the coordinator waits this long; members still
# unhealthy/absent at expiry are evicted and the survivors re-form into a
# smaller slice under the next generation (workloads restart from
# checkpoint under the new identity — see docs/user-guide/resilience.md
# §Reshape runbook).
SLICE_RESHAPE_GRACE_S = 0.0

# Env overrides for the --slice-* flags (DaemonSets set env more easily
# than per-node args).
ENV_SLICE_RENDEZVOUS = "TPU_DP_SLICE_RENDEZVOUS"
ENV_SLICE_WORKERS = "TPU_DP_SLICE_WORKERS"
ENV_SLICE_RESHAPE_GRACE = "TPU_DP_SLICE_RESHAPE_GRACE_S"

# Flight recorder (PR 4): where the crash-safe event-journal dump lands
# on exit/SIGTERM.  The DaemonSet mounts a hostPath here so the
# post-mortem survives the pod; empty disables the dump.
FLIGHT_RECORD_DIR = "/var/lib/tpu-flight-records"
ENV_FLIGHT_RECORD_DIR = "TPU_DP_FLIGHT_RECORD_DIR"

# Incident bundles (PR 19): where alert-triggered incident bundles land
# (alert history + journal + TSDB snapshot + continuous-profile slice).
# Mounted as a hostPath next to the flight records; empty disables the
# incident subscriber entirely.
INCIDENT_DIR = "/var/lib/tpu-incidents"
ENV_INCIDENT_DIR = "TPU_DP_INCIDENT_DIR"
