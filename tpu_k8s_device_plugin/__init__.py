"""Kubernetes device plugin and node labeller for Google Cloud TPUs.

A TPU-native rebuild of ROCm/k8s-device-plugin (see SURVEY.md): the kubelet-facing
agents are Python + grpcio, hardware probing is a C++ shim (native/tpuprobe), and
example workloads are JAX/XLA.
"""

__version__ = "0.1.0"
