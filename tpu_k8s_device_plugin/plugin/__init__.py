"""Kubelet-facing plugin adapter (≈ internal/pkg/plugin)."""

from .plugin import TpuDevicePlugin

__all__ = ["TpuDevicePlugin"]
