"""Plugin adapter: implements the kubelet DevicePluginServer, delegating
every RPC to a DeviceImpl.

TPU-native analog of AMDGPUPlugin
(/root/reference/internal/pkg/plugin/plugin.go:44-186): owns the heartbeat
and stop signalling for the ListAndWatch stream; all device knowledge lives
behind the DeviceImpl contract.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import List, Optional

import grpc

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.proto import (
    deviceplugin_pb2 as pluginapi,
    deviceplugin_pb2_grpc as pluginapi_grpc,
)
from tpu_k8s_device_plugin.types import (
    DeviceImpl,
    DevicePluginContext,
    constants,
)

log = logging.getLogger(__name__)

_BEAT = "beat"
_STOP = "stop"


class PluginMetrics:
    """Per-resource latency instruments shared by every plugin a
    manager serves (one family, ``resource`` label).  Lives on the
    manager's obs.Registry so the debug /metrics surface renders it."""

    def __init__(self, registry: obs.Registry):
        self.allocate_seconds = registry.histogram(
            "tpu_plugin_allocate_seconds",
            "Allocate RPC latency (env/mount/device-spec build).",
            ("resource",), buckets=obs.FAST_BUCKETS_S)
        self.frame_seconds = registry.histogram(
            "tpu_plugin_list_and_watch_frame_seconds",
            "Building one ListAndWatch frame (enumeration or health "
            "refresh + response construction).",
            ("resource",), buckets=obs.FAST_BUCKETS_S)
        self.probe_seconds = registry.histogram(
            "tpu_plugin_health_probe_seconds",
            "One health probe (DeviceImpl.update_health) on a beat.",
            ("resource",), buckets=obs.FAST_BUCKETS_S)


class TpuDevicePlugin(pluginapi_grpc.DevicePluginServicer):
    """One instance serves one resource name."""

    def __init__(self, device_impl: DeviceImpl, ctx: DevicePluginContext,
                 metrics: Optional[PluginMetrics] = None,
                 recorder: Optional[obs.FlightRecorder] = None):
        self.impl = device_impl
        self.ctx = ctx
        self.metrics = metrics
        # flight recorder (PR 4): Allocate spans and device health
        # transitions journal here so a post-mortem can say WHICH
        # device demoted, when, and in which trace
        self.recorder = recorder
        self._lock = threading.Lock()
        self._watchers: List[queue.Queue] = []
        self._stopped = False
        # RPC counters for the debug endpoint (SURVEY §5 observability);
        # ints mutated under _lock so the debug reader sees consistent values
        self.rpc_counts = {
            "allocate": 0,
            "get_preferred_allocation": 0,
            "list_and_watch_streams": 0,
        }
        # last device list sent down any ListAndWatch stream — the debug
        # endpoint serves this instead of re-probing hardware per request
        # (published by reference assignment; lists are never mutated)
        self.last_devices: Optional[List] = None

    def _count(self, rpc: str) -> None:
        with self._lock:
            self.rpc_counts[rpc] += 1

    def counters(self) -> dict:
        """Consistent copy of the RPC counters (debug surface)."""
        with self._lock:
            return dict(self.rpc_counts)

    def _record_health_diff(self, prev, devices, trace) -> None:
        """Journal per-device health transitions between two
        ListAndWatch frames: the discrete demotion/recovery events a
        post-mortem needs (the gauges only show the rollup)."""
        if self.recorder is None or prev is None:
            return
        prev_map = {d.ID: d.health for d in prev}
        for d in devices:
            old = prev_map.get(d.ID)
            if old is None or old == d.health:
                continue
            self.recorder.record(
                "tpu_device_recovered" if d.health == constants.HEALTHY
                else "tpu_device_demoted",
                trace=trace, device=d.ID,
                resource=self.ctx.resource_name(),
                health=d.health, was=old)

    # -- lifecycle signalling (≈ plugin.go heartbeat/signal channels) -------

    def beat(self) -> None:
        """Pulse: every open ListAndWatch stream re-probes health and
        resends its device list."""
        with self._lock:
            for q in self._watchers:
                q.put(_BEAT)

    def stop(self) -> None:
        """Terminate all ListAndWatch streams (plugin shutdown)."""
        with self._lock:
            self._stopped = True
            for q in self._watchers:
                q.put(_STOP)

    def start(self) -> None:
        """Called after construction, before kubelet registration
        (≈ AMDGPUPlugin.Start → DeviceImpl.Start, plugin.go:116-120)."""
        self.impl.start(self.ctx)

    # -- DevicePluginServer RPCs -------------------------------------------

    def GetDevicePluginOptions(self, request, context):
        try:
            return self.impl.get_options(self.ctx)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def ListAndWatch(self, request, context):
        """Initial device list, then health-refreshed resends on every
        heartbeat (≈ plugin.go:146-170)."""
        t0 = time.perf_counter()
        # one ROOT trace per stream: every frame and health transition
        # this stream produces shares it, so "what happened on this
        # kubelet watch" is a single /debug/traces query
        stream_trace = obs.new_trace()
        try:
            devices = self.impl.enumerate(self.ctx)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))
            return
        # register the watcher before the first send so a beat() arriving
        # while the initial frame is in flight is never dropped
        q: queue.Queue = queue.Queue()
        with self._lock:
            if self._stopped:
                return
            self._watchers.append(q)
            self.rpc_counts["list_and_watch_streams"] += 1
        # client disconnect must unblock q.get() — otherwise every kubelet
        # restart leaks one executor thread parked in get() forever
        context.add_callback(lambda: q.put(_STOP))
        try:
            self.last_devices = devices
            frame = pluginapi.ListAndWatchResponse(devices=devices)
            if self.metrics:
                self.metrics.frame_seconds.labels(
                    resource=self.ctx.resource_name()).observe(
                        time.perf_counter() - t0)
            if self.recorder is not None:
                self.recorder.record(
                    "tpu_plugin_list_and_watch_frame",
                    trace=stream_trace,
                    resource=self.ctx.resource_name(),
                    devices=len(devices),
                    unhealthy=sum(d.health != constants.HEALTHY
                                  for d in devices),
                    duration_s=time.perf_counter() - t0)
            yield frame
            while context.is_active():
                msg = q.get()
                if msg == _STOP:
                    log.info(
                        "ListAndWatch(%s): stop signal, closing stream",
                        self.ctx.resource_name(),
                    )
                    return
                t0 = time.perf_counter()
                try:
                    devices = self.impl.update_health(self.ctx)
                except Exception as e:
                    log.error("UpdateHealth failed: %s", e)
                    continue
                finally:
                    # probe duration records failed probes too — a
                    # probe that times out is exactly the latency an
                    # operator needs to see
                    if self.metrics:
                        self.metrics.probe_seconds.labels(
                            resource=self.ctx.resource_name()).observe(
                                time.perf_counter() - t0)
                self._record_health_diff(self.last_devices, devices,
                                         stream_trace)
                self.last_devices = devices
                frame = pluginapi.ListAndWatchResponse(devices=devices)
                if self.metrics:
                    self.metrics.frame_seconds.labels(
                        resource=self.ctx.resource_name()).observe(
                            time.perf_counter() - t0)
                yield frame
        finally:
            with self._lock:
                if q in self._watchers:
                    self._watchers.remove(q)

    def GetPreferredAllocation(self, request, context):
        self._count("get_preferred_allocation")
        try:
            return self.impl.get_preferred_allocation(self.ctx, request)
        except Exception as e:
            log.error("GetPreferredAllocation failed: %s", e)
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def Allocate(self, request, context):
        self._count("allocate")
        # span: latency histogram + a trace-tagged log line per grant
        # (outcome=error when impl.allocate raises → context.abort).
        # Each Allocate opens a ROOT trace tagged with the granted
        # device ids: the id in the span line / exemplar / recorder
        # event is what stitches a pod's placement to later demotions
        device_ids = [d for cr in request.container_requests
                      for d in cr.devices_ids]
        with obs.span(
            "tpu_plugin_allocate",
            histogram=self.metrics.allocate_seconds if self.metrics
            else None,
            labels={"resource": self.ctx.resource_name()},
            logger=log, trace=obs.new_trace(), recorder=self.recorder,
        ) as sp:
            sp.annotate(containers=len(request.container_requests),
                        devices=",".join(device_ids) or "-")
            try:
                return self.impl.allocate(self.ctx, request)
            except Exception as e:
                log.error("Allocate failed: %s", e)
                context.abort(grpc.StatusCode.INTERNAL, str(e))

    def PreStartContainer(self, request, context):
        # Not required (pre_start_required=false), but answer gracefully.
        return pluginapi.PreStartContainerResponse()
