"""Policy interface (≈ reference allocator/allocator.go:27-30)."""

from __future__ import annotations

import abc
from typing import List, Optional, Sequence, TYPE_CHECKING

if TYPE_CHECKING:
    from tpu_k8s_device_plugin.tpu.topology import IciTopology
    from .device import AllocDevice


class AllocationError(Exception):
    """Raised when a preferred allocation cannot be computed; the plugin
    surfaces this to the kubelet, which falls back to default allocation."""


def first_fit(
    available_ids: Sequence[str],
    required_ids: Sequence[str],
    size: int,
) -> List[str]:
    """Kubelet-default selection: required ids first, then available ones in
    order until *size*.  The degraded answer every impl gives when no
    topology-aware policy is usable."""
    ids = list(required_ids)
    for dev_id in available_ids:
        if len(ids) >= size:
            break
        if dev_id not in ids:
            ids.append(dev_id)
    return ids[:size]


class Policy(abc.ABC):
    """Preferred-allocation policy: precompute weights at init, answer
    admission-time subset queries from memory only (the precompute-at-init
    shape that keeps GetPreferredAllocation fast, SURVEY.md §3.3/§3.4)."""

    @abc.abstractmethod
    def init(
        self,
        devices: Sequence["AllocDevice"],
        topology: Optional["IciTopology"] = None,
    ) -> None:
        """Build the pairwise weight table for *devices*."""

    @abc.abstractmethod
    def allocate(
        self,
        available_ids: Sequence[str],
        required_ids: Sequence[str],
        size: int,
    ) -> List[str]:
        """Pick *size* device ids from *available_ids* including all
        *required_ids*, minimising total pairwise weight."""
