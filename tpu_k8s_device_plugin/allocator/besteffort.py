"""Best-effort topology-aware allocation policy.

Keeps the reference's contract and validation semantics
(besteffort_policy.go:88-151) with a TPU-first candidate search:

1. **Sub-mesh pass** — enumerate contiguous rectangular boxes on the ICI
   grid that exactly cover the request (squarest first).  These are the
   shapes XLA's ICI collectives want; on a grid they are also the global
   pairwise-weight minima.
2. **Anti-fragmentation fill** — for partitioned chips, try to satisfy the
   request from the fewest chips, preferring chips with the fewest free
   partitions (hole-filling, ≈ device.go:375-440).
3. **Greedy multi-seed fallback** — grow sets by minimum added pairwise
   weight from every seed; covers irregular sizes and fragmented
   availability.  Polynomial, unlike the reference's BFS subset combine.

The lowest total pairwise weight wins; ties break to fewer distinct chips,
then lowest chip/core indices, keeping results deterministic for the
table-driven tests (≈ besteffort_policy_test.go's exact expected subsets).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from tpu_k8s_device_plugin.tpu.topology import IciTopology
from .allocator import AllocationError, Policy
from .device import (
    AllocDevice,
    WeightModel,
    enumerate_submesh_candidates,
    group_by_parent,
)


class BestEffortPolicy(Policy):
    def __init__(self) -> None:
        self._model: Optional[WeightModel] = None
        self._topology: Optional[IciTopology] = None
        self._by_coord: Dict[Tuple[int, int, int], List[AllocDevice]] = {}
        self._groups: Dict[str, List[AllocDevice]] = {}

    def init(
        self,
        devices: Sequence[AllocDevice],
        topology: Optional[IciTopology] = None,
    ) -> None:
        if not devices:
            raise AllocationError("no devices to initialise policy with")
        ids = [d.id for d in devices]
        if len(set(ids)) != len(ids):
            raise AllocationError("duplicate device ids")
        self._topology = topology
        self._model = WeightModel(devices, topology)
        self._by_coord = {}
        for d in devices:
            self._by_coord.setdefault(d.coords, []).append(d)
        for devs in self._by_coord.values():
            devs.sort(key=lambda d: d.core_index)
        # Parent grouping is static after init; only availability-dependent
        # free counts are derived per call (precompute-at-init, SURVEY §3.3).
        self._groups = group_by_parent(devices)

    # -- validation mirrors besteffort_policy.go:88-124 ---------------------
    def allocate(
        self,
        available_ids: Sequence[str],
        required_ids: Sequence[str],
        size: int,
    ) -> List[str]:
        if self._model is None:
            raise AllocationError("policy not initialised")
        if size <= 0:
            raise AllocationError("allocation size must be a positive integer")
        if len(available_ids) < size:
            raise AllocationError(
                f"allocation size {size} exceeds {len(available_ids)} available"
            )
        if len(required_ids) > size:
            raise AllocationError("more required devices than allocation size")
        model = self._model
        unknown = [i for i in list(available_ids) + list(required_ids)
                   if i not in model.by_id]
        if unknown:
            raise AllocationError(f"unknown device ids: {unknown}")
        if not set(required_ids) <= set(available_ids):
            raise AllocationError("required devices not all available")
        if len(available_ids) == size:
            return self._ordered(available_ids)
        if len(required_ids) == size:
            return self._ordered(required_ids)

        available = frozenset(available_ids)
        required = frozenset(required_ids)

        # Free-partition counts per chip under *this* availability, for the
        # hole-filling tie-break (≈ filterPartitions' fewest-free-first sort,
        # device.go:342-349).
        free_count = {
            p: sum(1 for d in devs if d.id in available)
            for p, devs in self._groups.items()
        }

        # Contiguous rectangular sub-meshes take strict priority: an
        # L-shaped blob can score marginally lower on pairwise weight than a
        # 1xN strip, but only the contiguous shape gives the workload a real
        # ICI sub-mesh for XLA collectives.
        candidates = self._submesh_candidates(size, available, required)
        if not candidates:
            candidates = self._fill_candidates(size, available, required)
            candidates.extend(
                self._greedy_candidates(size, available, required, free_count)
            )
        if not candidates:
            raise AllocationError("no candidate subsets found")

        best = min(candidates, key=lambda c: self._candidate_key(c, free_count))
        return self._ordered([d.id for d in best])

    # -- candidate generators ----------------------------------------------

    def _submesh_candidates(self, size, available, required):
        topo = self._topology
        if topo is None:
            return []
        # slice wraparound reaches the local grid only on axes this host
        # spans entirely (host_bounds 1): otherwise the seam is between
        # hosts, not between our local edge chips
        wrap = tuple(
            topo.wrap[i] and topo.host_bounds[i] == 1 for i in range(3)
        )
        return enumerate_submesh_candidates(
            self._by_coord,
            topo.chips_per_host_bounds,
            size,
            available,
            required,
            wrap=wrap,
        )

    def _fill_candidates(self, size, available, required):
        """Satisfy from as few chips as possible, filling the least-free
        chips first (anti-fragmentation, ≈ device.go:310-442)."""
        model = self._model
        req_devs = [model.by_id[i] for i in required]
        req_parents = {d.parent_id for d in req_devs}

        free: List[Tuple[str, List[AllocDevice]]] = []
        for parent, devs in self._groups.items():
            f = [d for d in devs if d.id in available and d.id not in required]
            if f:
                free.append((parent, f))
        # fewest free partitions first; required chips' leftovers before
        # untouched chips; parent id as final deterministic tie-break
        free.sort(key=lambda pf: (pf[0] not in req_parents, len(pf[1]), pf[0]))

        chosen = list(req_devs)
        for _parent, devs in free:
            for d in devs:
                if len(chosen) == size:
                    break
                chosen.append(d)
            if len(chosen) == size:
                break
        return [chosen] if len(chosen) == size else []

    def _greedy_candidates(self, size, available, required, free_count):
        model = self._model
        req_devs = [model.by_id[i] for i in required]
        pool = [model.by_id[i] for i in available if i not in required]

        def grow(seed: List[AllocDevice]) -> Optional[List[AllocDevice]]:
            chosen = list(seed)
            chosen_ids = {d.id for d in chosen}
            while len(chosen) < size:
                best_d, best_key = None, None
                for d in pool:
                    if d.id in chosen_ids:
                        continue
                    delta = sum(model.weight(d.id, c.id) for c in chosen)
                    key = (delta, free_count[d.parent_id], d.sort_key)
                    if best_key is None or key < best_key:
                        best_d, best_key = d, key
                if best_d is None:
                    return None
                chosen.append(best_d)
                chosen_ids.add(best_d.id)
            return chosen

        out = []
        if req_devs:
            grown = grow(req_devs)
            if grown:
                out.append(grown)
        else:
            for seed in pool:
                grown = grow([seed])
                if grown:
                    out.append(grown)
        return out

    # -- selection ----------------------------------------------------------

    def _candidate_key(self, devs: List[AllocDevice], free_count):
        ids = [d.id for d in devs]
        parents = {d.parent_id for d in devs}
        return (
            self._model.set_weight(ids),
            len(parents),
            # hole-filling: prefer chips with fewer free partitions left
            sum(free_count.get(p, 0) for p in parents),
            sorted(d.sort_key for d in devs),
        )

    def _ordered(self, ids) -> List[str]:
        model = self._model
        return sorted(ids, key=lambda i: model.by_id[i].sort_key)
