"""Topology-aware preferred-allocation policies.

TPU-native analog of the reference's ``internal/pkg/allocator``
(/root/reference/internal/pkg/allocator/): same Policy contract and
best-effort pairwise-weight shape, but the weights come from ICI hop
distance on the chip grid instead of KFD XGMI/PCIe link parsing, and
candidate generation prefers contiguous rectangular ICI sub-meshes —
the shapes XLA collectives ride efficiently.
"""

from .allocator import AllocationError, Policy, first_fit
from .device import AllocDevice, WeightModel, devices_from_discovery
from .besteffort import BestEffortPolicy

__all__ = [
    "AllocationError",
    "AllocDevice",
    "BestEffortPolicy",
    "Policy",
    "first_fit",
    "WeightModel",
    "devices_from_discovery",
]
