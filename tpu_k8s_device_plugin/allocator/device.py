"""Allocator device model, ICI weight computation, sub-mesh enumeration.

TPU-native analog of reference allocator/device.go.  The reference derives
pairwise weights from KFD io_links/p2p_links (XGMI type 11 = 10, PCIe type 2
= 40, NUMA affinity ±10; device.go:37-54,135-218).  TPU chips on a host are
all ICI-connected in a grid, so the weight is the ICI hop count itself, and
the structural trick (device.go:310-442's per-GPU grouping) becomes stronger:
only contiguous rectangular sub-meshes are worth enumerating first.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tpu_k8s_device_plugin.tpu.discovery import TpuDevice
from tpu_k8s_device_plugin.tpu.topology import IciTopology

# Weight constants.  Same scale as the reference's (device.go:37-54) so the
# design doc's worked examples translate: intra-chip partitions are nearly
# free, each ICI hop costs one XGMI-unit, PCIe-only (no ICI info) is 2-4x.
WEIGHT_SAME_CHIP = 5          # two TensorCore partitions of one chip
WEIGHT_PER_ICI_HOP = 10       # per ICI hop between chips
WEIGHT_NUMA_PENALTY = 2       # added when chips sit under different NUMA nodes
WEIGHT_PCIE_SAME_NUMA = 20    # no ICI data: same-NUMA PCIe
WEIGHT_PCIE_DIFF_NUMA = 40    # no ICI data: cross-NUMA PCIe


@dataclass(frozen=True)
class AllocDevice:
    """One allocatable device: a whole chip, or one TensorCore partition."""

    id: str                   # kubelet device id
    parent_id: str            # PCI address of the owning chip
    chip_index: int           # discovery ordinal of the owning chip (NOT the
                              # raw accel index, which is -1 on passthrough
                              # hosts; the ordinal keeps ordering deterministic)
    core_index: int = 0       # partition index within the chip (0 for whole)
    coords: Tuple[int, int, int] = (0, 0, 0)
    numa_node: int = 0

    @property
    def sort_key(self) -> Tuple[int, int]:
        return (self.chip_index, self.core_index)


def devices_from_discovery(
    chips: Dict[str, TpuDevice], partitioned: Optional[bool] = None
) -> List[AllocDevice]:
    """Expand discovered chips into allocatable devices.

    Chips in "core" partition mode contribute one AllocDevice per TensorCore
    with ids ``<pci>#core<k>`` (the partition-device analog of the
    reference's amdgpu_xcp_* ids); whole chips contribute themselves.  When
    *partitioned* is given, only chips of that granularity are included
    (mixed naming runs one policy per resource).
    """
    out: List[AllocDevice] = []
    ordered = sorted(
        chips.values(), key=lambda c: (c.accel_index < 0, c.accel_index, c.id)
    )
    for ordinal, chip in enumerate(ordered):
        is_core = chip.partition_mode == "core"
        if partitioned is not None and is_core != partitioned:
            continue
        if is_core:
            for k in range(chip.cores_per_chip):
                out.append(
                    AllocDevice(
                        id=f"{chip.id}#core{k}",
                        parent_id=chip.id,
                        chip_index=ordinal,
                        core_index=k,
                        coords=chip.coords,
                        numa_node=chip.numa_node,
                    )
                )
        else:
            out.append(
                AllocDevice(
                    id=chip.id,
                    parent_id=chip.id,
                    chip_index=ordinal,
                    coords=chip.coords,
                    numa_node=chip.numa_node,
                )
            )
    return out


class WeightModel:
    """Precomputed pairwise weights between devices
    (≈ fetchAllPairWeights, device.go:220-252)."""

    def __init__(
        self,
        devices: Sequence[AllocDevice],
        topology: Optional[IciTopology] = None,
    ):
        self.devices = list(devices)
        self.by_id: Dict[str, AllocDevice] = {d.id: d for d in devices}
        self.topology = topology
        self._weights: Dict[Tuple[str, str], int] = {}
        for a, b in itertools.combinations(self.devices, 2):
            w = self._pair_weight(a, b)
            self._weights[(a.id, b.id)] = w
            self._weights[(b.id, a.id)] = w

    def _pair_weight(self, a: AllocDevice, b: AllocDevice) -> int:
        if a.parent_id == b.parent_id:
            return WEIGHT_SAME_CHIP
        topo = self.topology
        if topo is not None and topo.local_chip_count > 0:
            hops = topo.coord_distance(a.coords, b.coords)
            w = WEIGHT_PER_ICI_HOP * max(hops, 1)
            if a.numa_node != b.numa_node:
                w += WEIGHT_NUMA_PENALTY
            return w
        return (
            WEIGHT_PCIE_SAME_NUMA
            if a.numa_node == b.numa_node
            else WEIGHT_PCIE_DIFF_NUMA
        )

    def weight(self, a_id: str, b_id: str) -> int:
        if a_id == b_id:
            return 0
        return self._weights[(a_id, b_id)]

    def set_weight(self, subset: Iterable[str]) -> int:
        ids = list(subset)
        return sum(
            self.weight(x, y) for x, y in itertools.combinations(ids, 2)
        )


def enumerate_submesh_candidates(
    devices_by_coord: Dict[Tuple[int, int, int], List[AllocDevice]],
    bounds: Tuple[int, int, int],
    size: int,
    available: frozenset,
    required: frozenset,
    wrap: Tuple[bool, bool, bool] = (False, False, False),
) -> List[List[AllocDevice]]:
    """All axis-aligned boxes on the chip grid whose devices exactly cover
    *size*, are fully available, and contain every required device.

    This is the TPU-structural replacement for the reference's BFS subset
    combine (device.go:405-440): on an ICI grid only contiguous rectangles
    minimise collective latency, and there are only O(X²Y²Z²) of them —
    SURVEY.md §7 "hard parts" notes the sub-mesh constraint shrinks the
    search space; exploit it.  On torus axes (v4/v5p) boxes may cross the
    wraparound seam: a segment spanning the edge is just as contiguous in
    ICI terms as an interior one.
    """
    out: List[List[AllocDevice]] = []
    per_chip = 0
    for devs in devices_by_coord.values():
        per_chip = max(per_chip, len(devs))
    if per_chip == 0 or size % per_chip != 0:
        return out
    target_chips = size // per_chip
    X, Y, Z = (max(b, 1) for b in bounds)

    def origins(extent: int, length: int, wraps: bool) -> range:
        # full-axis boxes have one distinct placement; wrap axes slide the
        # origin all the way around, others stop at the edge
        if length == extent:
            return range(1)
        return range(extent) if wraps else range(extent - length + 1)

    for w, h, d in _box_shapes(target_chips, (X, Y, Z)):
        for x0 in origins(X, w, wrap[0]):
            for y0 in origins(Y, h, wrap[1]):
                for z0 in origins(Z, d, wrap[2]):
                    chosen: List[AllocDevice] = []
                    ok = True
                    for dx in range(w):
                        for dy in range(h):
                            for dz in range(d):
                                coord = (
                                    (x0 + dx) % X,
                                    (y0 + dy) % Y,
                                    (z0 + dz) % Z,
                                )
                                devs = devices_by_coord.get(coord, [])
                                if len(devs) != per_chip or any(
                                    dev.id not in available for dev in devs
                                ):
                                    ok = False
                                    break
                                chosen.extend(devs)
                            if not ok:
                                break
                        if not ok:
                            break
                    if ok and required <= {dev.id for dev in chosen}:
                        out.append(chosen)
    return out


def _box_shapes(
    n: int, limits: Tuple[int, int, int]
) -> List[Tuple[int, int, int]]:
    """Factorisations of n into (w,h,d) fitting inside *limits*, squarest
    (smallest max-dimension, i.e. lowest-diameter sub-mesh) first."""
    shapes = []
    X, Y, Z = limits
    for w in range(1, min(n, X) + 1):
        if n % w:
            continue
        rest = n // w
        for h in range(1, min(rest, Y) + 1):
            if rest % h:
                continue
            d = rest // h
            if d <= Z:
                shapes.append((w, h, d))
    shapes.sort(key=lambda s: (max(s), sorted(s, reverse=True)))
    return shapes


def group_by_parent(
    devices: Iterable[AllocDevice],
) -> Dict[str, List[AllocDevice]]:
    """Partitions grouped by owning chip (≈ groupPartitionsByDevId,
    device.go:287-304)."""
    out: Dict[str, List[AllocDevice]] = {}
    for d in devices:
        out.setdefault(d.parent_id, []).append(d)
    for devs in out.values():
        devs.sort(key=lambda d: d.core_index)
    return out
