"""Prometheus surface of the tpu-metrics-exporter daemon.

The AMD daemon this subsystem mirrors is a *metrics* exporter first —
the reference's health client dials
``amdgpu_device_metrics_exporter_grpc.socket``
(/root/reference/internal/pkg/exporter/health.go:35-37) and the health
RPC is one service on it.  Round 3 shipped the gRPC health half only;
this module adds the Prometheus half: a ``/metrics`` HTTP endpoint with
per-chip health gauges and error counters, rendered through the repo's
shared :mod:`tpu_k8s_device_plugin.obs` registry (each server owns its
own Registry instance, so no client-library-style global state leaks
between tests).

Exported series (full reference: docs/user-guide/observability.md):

- ``tpu_device_health{chip,device} 0|1`` — per-chip gauge, same probe
  as the gRPC health RPC (sysfs chip_state / UE count / node stat)
- ``tpu_device_uncorrectable_errors_total{chip}`` — driver-reported
  fatal error count (present only when the sysfs attr exists)
- ``tpu_exporter_chips`` / ``tpu_exporter_unhealthy_chips`` — node
  rollups so one scrape answers "is this node degraded"
- ``tpu_exporter_scrapes_total`` — exporter liveness
- ``tpu_exporter_probe_seconds`` — probe-walk latency histogram
"""

from __future__ import annotations

import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.tpu import discovery, sysfs
from tpu_k8s_device_plugin.types import constants

from .server import granular_health_available, probe_chip_states

log = logging.getLogger(__name__)

# label escaping lives in obs now (it used to be private here, and the
# plugin debug renderer reached in for it); kept as an alias for any
# external importer of the old name
_escape = obs.escape_label_value


def read_ue_count(sysfs_root: str, pci_address: str) -> Optional[int]:
    """Driver-reported uncorrectable-error count for a chip, or None when
    the attribute is absent (older drivers) or unparseable."""
    raw = sysfs.read_file(os.path.join(
        sysfs_root, "bus", "pci", "devices", pci_address,
        constants.SYSFS_UE_COUNT))
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def update_metrics(sysfs_root: str = "/sys", dev_root: str = "/dev",
                   scrapes: int = 0,
                   registry: Optional[obs.Registry] = None
                   ) -> obs.Registry:
    """One probe pass: walk every chip and refresh the health
    instruments on *registry* (a fresh one when None).  Split from
    :func:`render_metrics` so the HTTP server can run it as a
    render-time collect hook — the in-process TSDB's sampling tick
    then sees fresh probes, not the last scrape's leftovers."""
    reg = registry if registry is not None else obs.Registry()
    t0 = time.perf_counter()
    chips, _ = discovery.get_tpu_chips(sysfs_root, dev_root, "/nonexistent")
    states = probe_chip_states(sysfs_root, dev_root, chips=chips)
    probe_dt = time.perf_counter() - t0

    health = reg.gauge(
        "tpu_device_health", "Per-chip health (1 healthy, 0 unhealthy).",
        ("chip", "device"))
    ue = reg.counter(
        "tpu_device_uncorrectable_errors_total",
        "Driver-reported fatal error count.", ("chip",))
    # per-chip label sets rebuild from scratch: an unplugged chip must
    # not leave a stale series in a long-lived registry
    health.clear()
    ue.clear()
    unhealthy = 0
    for cid in sorted(states):
        st = states[cid]
        up = 1 if st.health == "Healthy" else 0
        unhealthy += 1 - up
        health.labels(chip=cid, device=st.device).set(up)
        chip = chips.get(cid)
        if chip is not None:
            n = read_ue_count(sysfs_root, chip.pci_address)
            if n is not None:
                ue.labels(chip=cid)._set(n)
    reg.gauge(
        "tpu_exporter_granular_health",
        "Driver exposes chip_state/UE attrs (0 = wedged-chip detection "
        "degraded to node stats).",
    ).set(1 if chips and granular_health_available(sysfs_root, chips)
          else 0)
    reg.gauge("tpu_exporter_chips", "Chips the exporter probes.").set(
        len(states))
    reg.gauge("tpu_exporter_unhealthy_chips",
              "Chips currently unhealthy.").set(unhealthy)
    reg.counter("tpu_exporter_scrapes_total", "Scrapes served.")._set(
        scrapes)
    reg.histogram(
        "tpu_exporter_probe_seconds",
        "One full probe walk (discovery + per-chip sysfs state).",
        buckets=obs.FAST_BUCKETS_S).observe(probe_dt)
    return reg


def render_metrics(sysfs_root: str = "/sys", dev_root: str = "/dev",
                   scrapes: int = 0,
                   registry: Optional[obs.Registry] = None,
                   openmetrics: bool = False) -> str:
    """One scrape: probe every chip and render the exposition text
    through the shared :class:`obs.Registry` renderer.

    *registry* keeps instruments alive across scrapes (the HTTP server
    passes its own, so the probe-duration histogram accumulates); bare
    calls get a fresh one — no state leaks between tests.

    Rename (PR 3, promlint): ``tpu_device_uncorrectable_errors`` is now
    ``tpu_device_uncorrectable_errors_total`` (counters must end in
    ``_total``).  The render itself is accounted via
    :class:`obs.ScrapeMeta` (``tpu_scrape_*`` — PR 18)."""
    reg = update_metrics(sysfs_root, dev_root, scrapes=scrapes,
                         registry=registry)
    return obs.ScrapeMeta(reg).render(openmetrics=openmetrics)


def default_exporter_alert_rules() -> "list[obs.AlertRule]":
    """The exporter's built-in rule: unhealthy chips are a ticket
    after a minute of dwell (one flapping probe must not page)."""
    return [obs.threshold_rule(
        "tpu_unhealthy_chips", "tpu_exporter_unhealthy_chips",
        ">", 0, for_s=60.0, severity="ticket",
        description="One or more TPU chips on this node have probed "
                    "unhealthy for over a minute.")]


class MetricsHTTPServer:
    """``/metrics`` (Prometheus) + ``/healthz`` + the PR-18 retention
    surface (``/debug/query``, ``/alerts``) on a TCP port, probing the
    same fixture-injectable sysfs/dev roots as the gRPC service."""

    def __init__(self, port: int = constants.METRICS_HTTP_PORT,
                 sysfs_root: str = "/sys", dev_root: str = "/dev",
                 host: str = "0.0.0.0",
                 alert_rules: Optional[list] = None,
                 tick_interval_s: float = 15.0,
                 profiler_hz: float = 19.0):
        self._port = port
        self._host = host
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root
        self._scrapes = 0
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._tick_interval_s = tick_interval_s
        # persistent across scrapes so the probe-duration histogram
        # accumulates a real distribution
        self.registry = obs.Registry()
        # probe refresh rides the registry's collect hook: every
        # render — an HTTP scrape OR a TSDB sampling tick — sees a
        # fresh probe walk, so retained series never go stale between
        # scrapes
        self.registry.on_collect(self._refresh)
        self.scrape_meta = obs.ScrapeMeta(self.registry)
        self.recorder = obs.FlightRecorder(registry=self.registry)
        self.tsdb = obs.TSDB(self.registry)
        rules = (list(alert_rules) if alert_rules is not None
                 else default_exporter_alert_rules())
        self.alerts = obs.AlertEvaluator(
            self.tsdb, rules, recorder=self.recorder)
        # continuous sampling profiler (PR 19): the exporter is mostly
        # idle, but a probe walk wedged on sysfs shows up here
        self.profiler = obs.SamplingProfiler(
            self.registry, hz=profiler_hz)

    def _refresh(self) -> None:
        with self._lock:
            n = self._scrapes
        update_metrics(self._sysfs_root, self._dev_root, scrapes=n,
                       registry=self.registry)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> "MetricsHTTPServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                from urllib.parse import parse_qsl, urlsplit

                parts = urlsplit(self.path)
                if parts.path == "/healthz":
                    self._send(200, "text/plain", "ok\n")
                    return
                if parts.path == "/alerts":
                    self._send(200, "application/json",
                               outer.alerts.status_json() + "\n")
                    return
                if parts.path == "/debug/query":
                    params = dict(parse_qsl(parts.query))
                    try:
                        body = outer.tsdb.handle_query_json(params)
                    except ValueError as e:
                        self._send(400, "text/plain", f"{e}\n")
                        return
                    self._send(200, "application/json", body + "\n")
                    return
                if parts.path == "/debug/pprof":
                    from urllib.parse import parse_qs
                    try:
                        ctype, body = outer.profiler.handle_pprof(
                            parse_qs(parts.query))
                    except ValueError as e:
                        self._send(400, "text/plain", f"{e}\n")
                        return
                    self._send(200, ctype, body)
                    return
                if parts.path != "/metrics":
                    self._send(404, "text/plain", "not found\n")
                    return
                with outer._lock:
                    outer._scrapes += 1
                # OpenMetrics negotiation for parity with the other
                # surfaces (the exporter records no exemplars today,
                # but a scraper asking for the format must get a
                # format-valid body with the # EOF terminator)
                om = obs.negotiate_openmetrics(
                    self.headers.get("Accept"))
                try:
                    # probe refresh runs inside render via the
                    # registry collect hook; ScrapeMeta accounts the
                    # exposition itself (tpu_scrape_*)
                    body = outer.scrape_meta.render(openmetrics=om)
                except Exception:  # scrape must not kill the daemon
                    log.exception("metrics scrape failed")
                    self._send(500, "text/plain",
                               "scrape failed; see exporter logs\n")
                    return
                self._send(200,
                           obs.OPENMETRICS_CONTENT_TYPE if om
                           else obs.TEXT_CONTENT_TYPE,
                           body)

            def _send(self, code, ctype, body: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):
                log.debug("metrics-http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        threading.Thread(target=self._httpd.serve_forever,
                         name="metrics-http", daemon=True).start()
        self.tsdb.start(self._tick_interval_s)
        self.profiler.start()
        log.info("prometheus metrics on http://%s:%d/metrics",
                 self._host, self.port)
        return self

    def stop(self) -> None:
        self.tsdb.stop()
        self.profiler.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
