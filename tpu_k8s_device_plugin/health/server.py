"""tpu-metrics-exporter: the probe daemon the plugin's health client talks to.

The reference only ships the *client* half (the AMD device-metrics-exporter
is a separate project); this build provides a working server too, so the
health path is testable end-to-end and deployable from one image.  The probe
re-enumerates the accel class, reads each chip's driver-reported health
attributes from sysfs (chip_state / uncorrectable_errors — the wedged-chip
state an open(2) could never see), and stat-checks the device node.  It
never open(2)s the chardev: the TPU accel driver is single-open, so an open
probe would flap busy chips Unhealthy and could race a launching workload's
own open (SURVEY §7 'health without privileged /dev/kfd': the probe must be
non-exclusive).
"""

from __future__ import annotations

import concurrent.futures
import errno
import logging
import os
from typing import Dict, Optional

import grpc

from tpu_k8s_device_plugin.proto import (
    tpuhealth_pb2 as hpb,
    tpuhealth_pb2_grpc as hpb_grpc,
)
from tpu_k8s_device_plugin.resilience import faults
from tpu_k8s_device_plugin.tpu import discovery, sysfs
from tpu_k8s_device_plugin.types import constants

log = logging.getLogger(__name__)

try:
    from tpu_k8s_device_plugin.hostinfo import tpuprobe as _tpuprobe
except Exception as _e:  # no native shim / no toolchain: portable fallback
    _tpuprobe = None
    log.warning(
        "native tpuprobe unavailable (%s); health probe degrades to "
        "access(2) checks", _e,
    )


# Probe errnos that genuinely mean "the chip is gone or the driver is
# broken".  Everything else is NOT a health verdict: -EBUSY would mean a
# workload holds the single-open chardev (alive and consumed — demoting it
# would drop allocatable capacity exactly when chips are busy and flap
# health on every pulse); -EACCES/-EPERM mean the probe lacks privilege,
# which says nothing about the silicon.  The native probe is stat-only and
# can't see EBUSY at all, but the policy is encoded here so any future
# probe mechanism inherits it.
_DEMOTE_ERRNOS = frozenset({errno.ENOENT, errno.ENXIO, errno.ENODEV, errno.EIO})


def _node_openable(path: str) -> bool:
    """Does the device node exist for a workload to consume?  Stat-only —
    see tp_probe_device: an open(2) probe on the single-open TPU chardev
    would flap busy chips and race workload launches."""
    if _tpuprobe is not None:
        rc = _tpuprobe.probe_device_node(path)
        if rc != -errno.ENOTSUP:
            return rc == 0 or -rc not in _DEMOTE_ERRNOS
        # exists but not a chardev: captured fixture trees model /dev/accelN
        # as regular files — fall through to the portable check
    return os.path.exists(path) and os.access(path, os.R_OK | os.W_OK)


def granular_health_available(sysfs_root: str, chips) -> bool:
    """Does the driver expose EITHER granular health attribute
    (chip_state / uncorrectable_errors) for any chip?  The attrs are
    modelled from the fixture ABI, not a cited driver source
    (testdata/README.md records the provenance per attribute) — so on
    a real host where the driver spells them differently, the granular
    path would silently never fire.  This predicate makes that state
    operator-visible: probe_chip_states warns once per tree and the
    exporter publishes ``tpu_exporter_granular_health``."""
    for chip in chips.values():
        pci_dir = os.path.join(
            sysfs_root, "bus", "pci", "devices", chip.pci_address)
        if (os.path.exists(os.path.join(
                pci_dir, constants.SYSFS_CHIP_STATE))
                or os.path.exists(os.path.join(
                    pci_dir, constants.SYSFS_UE_COUNT))):
            return True
    return False


_warned_no_granular: set = set()


def _sysfs_chip_fault(sysfs_root: str, pci_address: str) -> Optional[str]:
    """Granular driver-reported chip state from sysfs — the signal an
    open(2) probe cannot see (a wedged chip whose chardev still opens).
    Returns a human-readable fault reason, or None when healthy / the attrs
    are absent (older drivers expose neither; absence is not a verdict)."""
    pci_dir = os.path.join(sysfs_root, "bus", "pci", "devices", pci_address)
    state = sysfs.read_file(os.path.join(pci_dir, constants.SYSFS_CHIP_STATE))
    if state and state != constants.CHIP_STATE_ALIVE:
        return f"chip_state={state}"
    ue = sysfs.read_file(os.path.join(pci_dir, constants.SYSFS_UE_COUNT))
    if ue:
        try:
            if int(ue) > 0:
                return f"uncorrectable_errors={int(ue)}"
        except ValueError:
            log.warning("unparseable %s for %s: %r",
                        constants.SYSFS_UE_COUNT, pci_address, ue)
    return None


def probe_chip_states(
    sysfs_root: str = "/sys", dev_root: str = "/dev", chips=None
) -> Dict[str, hpb.TpuState]:
    """Probe every chip: driver-reported sysfs state first (sees wedged
    chips), then device-node accessibility (sees missing/broken nodes).
    *chips* skips the discovery walk when the caller already ran one
    (the Prometheus scrape renders health + error counters from a single
    enumeration)."""
    # chaos hook for the libtpu/sysfs probe itself (inert attribute
    # check when no fault spec is armed): `probe:hang:N` models a
    # wedged driver read, `probe:error:p` a probe crash
    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("probe")
    states: Dict[str, hpb.TpuState] = {}
    if chips is None:
        chips, _ = discovery.get_tpu_chips(
            sysfs_root, dev_root, "/nonexistent")
    if (chips and not granular_health_available(sysfs_root, chips)
            and sysfs_root not in _warned_no_granular):
        # absence-is-healthy is the right per-chip verdict (older
        # drivers legitimately omit the attrs), but a WHOLE tree
        # without them means wedged-chip detection is off — say so
        # once, instead of silently degrading to node-stat checks
        _warned_no_granular.add(sysfs_root)
        log.warning(
            "granular health unavailable: no chip under %s exposes "
            "%s or %s — wedged-chip detection degrades to device-node "
            "stat checks (see testdata/README.md for the attr "
            "provenance)", sysfs_root, constants.SYSFS_CHIP_STATE,
            constants.SYSFS_UE_COUNT)
    for chip in chips.values():
        if chip.accel_index < 0:
            # raw-PCI fallback chips (vfio passthrough) have no accel node to
            # probe; reporting them Healthy would mask the plugin's own
            # node-health fallback, so leave them out of the map entirely
            continue
        fault = _sysfs_chip_fault(sysfs_root, chip.pci_address)
        if fault is not None:
            log.warning("chip %s unhealthy: %s", chip.id, fault)
            healthy = False
        else:
            healthy = _node_openable(chip.dev_path)
        states[chip.id] = hpb.TpuState(
            id=chip.id,
            accel_index=chip.accel_index,
            health="Healthy" if healthy else "Unhealthy",
            device=chip.dev_path,
        )
    return states


class _Servicer(hpb_grpc.TpuHealthServiceServicer):
    def __init__(self, sysfs_root: str, dev_root: str):
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root

    def GetTpuState(self, request, context):
        states = probe_chip_states(self._sysfs_root, self._dev_root)
        state = states.get(request.id)
        if state is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"unknown chip {request.id}"
            )
        return hpb.GetTpuStateResponse(state=state)

    def List(self, request, context):
        states = probe_chip_states(self._sysfs_root, self._dev_root)
        return hpb.ListTpuStateResponse(
            states=[states[k] for k in sorted(states)]
        )


class TpuHealthServer:
    """Serves TpuHealthService on a unix socket."""

    def __init__(
        self,
        socket_path: str = constants.METRICS_EXPORTER_SOCKET,
        sysfs_root: str = "/sys",
        dev_root: str = "/dev",
    ):
        self.socket_path = socket_path
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root
        self._server: Optional[grpc.Server] = None

    def start(self) -> "TpuHealthServer":
        os.makedirs(os.path.dirname(self.socket_path), exist_ok=True)
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=4)
        )
        hpb_grpc.add_TpuHealthServiceServicer_to_server(
            _Servicer(self._sysfs_root, self._dev_root), self._server
        )
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        log.info("tpu-metrics-exporter serving on %s", self.socket_path)
        return self

    def wait(self) -> None:
        if self._server is not None:
            self._server.wait_for_termination()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait()
            self._server = None
        if os.path.exists(self.socket_path):
            try:
                os.remove(self.socket_path)
            except OSError:
                pass
