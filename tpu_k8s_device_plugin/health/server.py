"""tpu-metrics-exporter: the probe daemon the plugin's health client talks to.

The reference only ships the *client* half (the AMD device-metrics-exporter
is a separate project); this build provides a working server too, so the
health path is testable end-to-end and deployable from one image.  The probe
re-enumerates the accel class and verifies each chip's device node is
openable — a libtpu-free check that doesn't steal chip access from running
workloads (SURVEY §7 'health without privileged /dev/kfd': the probe must be
non-exclusive).
"""

from __future__ import annotations

import concurrent.futures
import errno
import logging
import os
from typing import Dict, Optional

import grpc

from tpu_k8s_device_plugin.proto import (
    tpuhealth_pb2 as hpb,
    tpuhealth_pb2_grpc as hpb_grpc,
)
from tpu_k8s_device_plugin.tpu import discovery
from tpu_k8s_device_plugin.types import constants

log = logging.getLogger(__name__)

try:
    from tpu_k8s_device_plugin.hostinfo import tpuprobe as _tpuprobe
except Exception as _e:  # no native shim / no toolchain: portable fallback
    _tpuprobe = None
    log.warning(
        "native tpuprobe unavailable (%s); health probe degrades to "
        "access(2) checks", _e,
    )


def _node_openable(path: str) -> bool:
    """Is the device node consumable by a workload?  The native probe
    actually opens the chardev (non-exclusive); access(2) can lie under
    capability-based permission schemes."""
    if _tpuprobe is not None:
        rc = _tpuprobe.probe_device_node(path)
        if rc != -errno.ENODEV:
            return rc == 0
        # not a chardev: captured fixture trees model /dev/accelN as
        # regular files — fall through to the portable check
    return os.path.exists(path) and os.access(path, os.R_OK | os.W_OK)


def probe_chip_states(
    sysfs_root: str = "/sys", dev_root: str = "/dev"
) -> Dict[str, hpb.TpuState]:
    """Probe every chip's presence + device-node accessibility."""
    states: Dict[str, hpb.TpuState] = {}
    chips, _ = discovery.get_tpu_chips(sysfs_root, dev_root, "/nonexistent")
    for chip in chips.values():
        if chip.accel_index < 0:
            # raw-PCI fallback chips (vfio passthrough) have no accel node to
            # probe; reporting them Healthy would mask the plugin's own
            # node-health fallback, so leave them out of the map entirely
            continue
        healthy = _node_openable(chip.dev_path)
        states[chip.id] = hpb.TpuState(
            id=chip.id,
            accel_index=chip.accel_index,
            health="Healthy" if healthy else "Unhealthy",
            device=chip.dev_path,
        )
    return states


class _Servicer(hpb_grpc.TpuHealthServiceServicer):
    def __init__(self, sysfs_root: str, dev_root: str):
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root

    def GetTpuState(self, request, context):
        states = probe_chip_states(self._sysfs_root, self._dev_root)
        state = states.get(request.id)
        if state is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"unknown chip {request.id}"
            )
        return hpb.GetTpuStateResponse(state=state)

    def List(self, request, context):
        states = probe_chip_states(self._sysfs_root, self._dev_root)
        return hpb.ListTpuStateResponse(
            states=[states[k] for k in sorted(states)]
        )


class TpuHealthServer:
    """Serves TpuHealthService on a unix socket."""

    def __init__(
        self,
        socket_path: str = constants.METRICS_EXPORTER_SOCKET,
        sysfs_root: str = "/sys",
        dev_root: str = "/dev",
    ):
        self.socket_path = socket_path
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root
        self._server: Optional[grpc.Server] = None

    def start(self) -> "TpuHealthServer":
        os.makedirs(os.path.dirname(self.socket_path), exist_ok=True)
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=4)
        )
        hpb_grpc.add_TpuHealthServiceServicer_to_server(
            _Servicer(self._sysfs_root, self._dev_root), self._server
        )
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        log.info("tpu-metrics-exporter serving on %s", self.socket_path)
        return self

    def wait(self) -> None:
        if self._server is not None:
            self._server.wait_for_termination()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait()
            self._server = None
        if os.path.exists(self.socket_path):
            try:
                os.remove(self.socket_path)
            except OSError:
                pass
