"""Client to the tpu-metrics-exporter health service.

TPU-native analog of the reference's exporter client
(/root/reference/internal/pkg/exporter/health.go:35-79): a short-lived
insecure gRPC connection over the exporter's unix socket per poll, mapping
device id → Healthy/Unhealthy.  Unreachable exporter returns {} — the
plugin then falls back to its own simple health check.

Resilience (PR 5): the once single-shot RPC now runs under the shared
:class:`~tpu_k8s_device_plugin.resilience.RetryPolicy` (a transient
exporter blip no longer costs a whole pulse of granular health), and
the ``health.list`` fault hook lets the chaos harness provoke exactly
that blip.  Hang containment lives one layer up: the device impl wraps
this whole probe in a breaker + watchdog (see
``device_impl._granular_health``).
"""

from __future__ import annotations

import logging
import os
from typing import Dict

import grpc

from tpu_k8s_device_plugin import resilience
from tpu_k8s_device_plugin.proto import (
    tpuhealth_pb2 as hpb,
    tpuhealth_pb2_grpc as hpb_grpc,
)
from tpu_k8s_device_plugin.resilience import faults
from tpu_k8s_device_plugin.types import constants

log = logging.getLogger(__name__)

# One retry after a short pause: enough to ride out an exporter restart
# between List and retry, short enough that a down exporter degrades
# this pulse to the simple health check instead of stalling it.
_LIST_RETRY = resilience.RetryPolicy(
    max_attempts=2, initial_backoff_s=0.2, max_backoff_s=1.0)


def get_tpu_health(
    socket_path: str = constants.METRICS_EXPORTER_SOCKET,
    timeout_s: float = constants.EXPORTER_HEALTH_CHECK_TIMEOUT_S,
    retry: "resilience.RetryPolicy" = None,
    metrics: "resilience.ResilienceMetrics" = None,
    recorder=None,
) -> Dict[str, str]:
    """Chip PCI address → "Healthy"/"Unhealthy" from the exporter daemon."""
    if not os.path.exists(socket_path):
        return {}

    def _list():
        if faults.ACTIVE is not None:
            faults.ACTIVE.fire("health.list")
        with grpc.insecure_channel(f"unix://{socket_path}") as ch:
            stub = hpb_grpc.TpuHealthServiceStub(ch)
            return stub.List(hpb.ListTpuStateRequest(), timeout=timeout_s)

    try:
        resp = (retry or _LIST_RETRY).call(
            _list, op="health.list",
            retry_on=(grpc.RpcError, faults.InjectedFault),
            metrics=metrics, recorder=recorder, logger=log)
    except (grpc.RpcError, faults.InjectedFault) as e:
        log.warning("tpu-metrics-exporter unreachable at %s: %s",
                    socket_path, e)
        return {}
    out: Dict[str, str] = {}
    for state in resp.states:
        health = state.health.strip().lower()
        out[state.id] = (
            constants.HEALTHY
            if health == "healthy"
            else constants.UNHEALTHY
        )
    return out
