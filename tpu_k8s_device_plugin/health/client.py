"""Client to the tpu-metrics-exporter health service.

TPU-native analog of the reference's exporter client
(/root/reference/internal/pkg/exporter/health.go:35-79): a short-lived
insecure gRPC connection over the exporter's unix socket per poll, mapping
device id → Healthy/Unhealthy.  Unreachable exporter returns {} — the
plugin then falls back to its own simple health check.
"""

from __future__ import annotations

import logging
import os
from typing import Dict

import grpc

from tpu_k8s_device_plugin.proto import (
    tpuhealth_pb2 as hpb,
    tpuhealth_pb2_grpc as hpb_grpc,
)
from tpu_k8s_device_plugin.types import constants

log = logging.getLogger(__name__)


def get_tpu_health(
    socket_path: str = constants.METRICS_EXPORTER_SOCKET,
    timeout_s: float = constants.EXPORTER_HEALTH_CHECK_TIMEOUT_S,
) -> Dict[str, str]:
    """Chip PCI address → "Healthy"/"Unhealthy" from the exporter daemon."""
    if not os.path.exists(socket_path):
        return {}
    out: Dict[str, str] = {}
    try:
        with grpc.insecure_channel(f"unix://{socket_path}") as ch:
            stub = hpb_grpc.TpuHealthServiceStub(ch)
            resp = stub.List(hpb.ListTpuStateRequest(), timeout=timeout_s)
        for state in resp.states:
            health = state.health.strip().lower()
            out[state.id] = (
                constants.HEALTHY
                if health == "healthy"
                else constants.UNHEALTHY
            )
    except grpc.RpcError as e:
        log.warning("tpu-metrics-exporter unreachable at %s: %s",
                    socket_path, e)
        return {}
    return out
