"""Health subsystem: exporter client + probe server (≈ internal/pkg/exporter)."""

from .client import get_tpu_health
from .metrics import MetricsHTTPServer, render_metrics
from .server import TpuHealthServer

__all__ = [
    "get_tpu_health",
    "MetricsHTTPServer",
    "render_metrics",
    "TpuHealthServer",
]
