#!/bin/sh
# Regenerate protobuf message modules.  The *_pb2_grpc.py files are
# hand-maintained (no grpcio-tools in the build image) — do not overwrite.
set -e
cd "$(dirname "$0")"
protoc --python_out=. deviceplugin.proto tpuhealth.proto
