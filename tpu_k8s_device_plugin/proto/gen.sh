#!/bin/sh
# Regenerate protobuf message modules.  The *_pb2_grpc.py files are
# hand-maintained (no grpcio-tools in the build image) — do not overwrite.
#
# slice_pb2.py has a no-protoc fallback: tools/gen_slice_pb2.py builds the
# descriptor with the protobuf python API (byte layout differs from protoc
# output, wire format does not).  With protoc installed, the protoc output
# below supersedes it.
set -e
cd "$(dirname "$0")"
if command -v protoc >/dev/null 2>&1; then
    protoc --python_out=. deviceplugin.proto tpuhealth.proto slice.proto
else
    echo "protoc not found; regenerating slice_pb2.py via descriptor_pb2" >&2
    python ../../tools/gen_slice_pb2.py
fi
