# -*- coding: utf-8 -*-
# Generated protocol buffer code.  DO NOT EDIT!
# source: slice.proto
#
# Built by proto/gen.sh's no-protoc fallback (tools/gen_slice_pb2.py):
# the build image ships protobuf but no protoc, so the serialized
# FileDescriptorProto below is constructed with descriptor_pb2 instead of
# compiled -- byte layout differs from protoc output, wire format does not.
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(b'\n\x0bslice.proto\x12\x08tpuslice"T\n\x0bJoinRequest\x12\x10\n\x08hostname\x18\x01 \x01(\t\x12\x0e\n\x06coords\x18\x02 \x03(\x05\x12\x12\n\nchip_count\x18\x03 \x01(\x05\x12\x0f\n\x07session\x18\x04 \x01(\t"\xa0\x01\n\nMembership\x12\x10\n\x08slice_id\x18\x01 \x01(\t\x12\x12\n\ngeneration\x18\x02 \x01(\x03\x12\x13\n\x0bnum_workers\x18\x03 \x01(\x05\x12\x11\n\thostnames\x18\x04 \x03(\t\x12\x1b\n\x13coordinator_address\x18\x05 \x01(\t\x12\x15\n\rreshaped_from\x18\x06 \x03(\t\x12\x10\n\x08degraded\x18\x07 \x01(\x08"x\n\x0cJoinResponse\x12\x0e\n\x06formed\x18\x01 \x01(\x08\x12\x0c\n\x04rank\x18\x02 \x01(\x05\x12\x0e\n\x06joined\x18\x03 \x01(\x05\x12\x10\n\x08expected\x18\x04 \x01(\x05\x12(\n\nmembership\x18\x05 \x01(\x0b2\x14.tpuslice.Membership"Y\n\x10HeartbeatRequest\x12\x10\n\x08hostname\x18\x01 \x01(\t\x12\x0f\n\x07healthy\x18\x02 \x01(\x08\x12\x0e\n\x06reason\x18\x03 \x01(\t\x12\x12\n\ngeneration\x18\x04 \x01(\x03"q\n\x11HeartbeatResponse\x12\x15\n\rslice_healthy\x18\x01 \x01(\x08\x12\x1b\n\x13unhealthy_hostnames\x18\x02 \x03(\t\x12(\n\nmembership\x18\x03 \x01(\x0b2\x14.tpuslice.Membership2\x8e\x01\n\x0fSliceRendezvous\x125\n\x04Join\x12\x15.tpuslice.JoinRequest\x1a\x16.tpuslice.JoinResponse\x12D\n\tHeartbeat\x12\x1a.tpuslice.HeartbeatRequest\x1a\x1b.tpuslice.HeartbeatResponseb\x06proto3')

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'slice_pb2', globals())
