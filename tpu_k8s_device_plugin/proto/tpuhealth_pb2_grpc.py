"""gRPC stubs/servicers for the TpuHealthService.

Hand-written in grpc_tools style; analog of the reference's generated
metricssvc_grpc.pb.go (MetricsService{GetGPUState, List}).
"""

import grpc

from . import tpuhealth_pb2 as api


class TpuHealthServiceStub:
    def __init__(self, channel: grpc.Channel):
        self.GetTpuState = channel.unary_unary(
            "/tpuhealth.TpuHealthService/GetTpuState",
            request_serializer=api.GetTpuStateRequest.SerializeToString,
            response_deserializer=api.GetTpuStateResponse.FromString,
        )
        self.List = channel.unary_unary(
            "/tpuhealth.TpuHealthService/List",
            request_serializer=api.ListTpuStateRequest.SerializeToString,
            response_deserializer=api.ListTpuStateResponse.FromString,
        )


class TpuHealthServiceServicer:
    def GetTpuState(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def List(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


def add_TpuHealthServiceServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "GetTpuState": grpc.unary_unary_rpc_method_handler(
            servicer.GetTpuState,
            request_deserializer=api.GetTpuStateRequest.FromString,
            response_serializer=api.GetTpuStateResponse.SerializeToString,
        ),
        "List": grpc.unary_unary_rpc_method_handler(
            servicer.List,
            request_deserializer=api.ListTpuStateRequest.FromString,
            response_serializer=api.ListTpuStateResponse.SerializeToString,
        ),
    }
    generic_handler = grpc.method_handlers_generic_handler(
        "tpuhealth.TpuHealthService", rpc_method_handlers
    )
    server.add_generic_rpc_handlers((generic_handler,))
