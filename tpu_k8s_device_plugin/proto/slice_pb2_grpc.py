"""gRPC stubs/servicers for the SliceRendezvous service.

Hand-written in grpc_tools style (same reason as the siblings: the build
image has grpcio but not grpcio-tools).
"""

import grpc

from . import slice_pb2 as api


class SliceRendezvousStub:
    def __init__(self, channel: grpc.Channel):
        self.Join = channel.unary_unary(
            "/tpuslice.SliceRendezvous/Join",
            request_serializer=api.JoinRequest.SerializeToString,
            response_deserializer=api.JoinResponse.FromString,
        )
        self.Heartbeat = channel.unary_unary(
            "/tpuslice.SliceRendezvous/Heartbeat",
            request_serializer=api.HeartbeatRequest.SerializeToString,
            response_deserializer=api.HeartbeatResponse.FromString,
        )


class SliceRendezvousServicer:
    def Join(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def Heartbeat(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


def add_SliceRendezvousServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "Join": grpc.unary_unary_rpc_method_handler(
            servicer.Join,
            request_deserializer=api.JoinRequest.FromString,
            response_serializer=api.JoinResponse.SerializeToString,
        ),
        "Heartbeat": grpc.unary_unary_rpc_method_handler(
            servicer.Heartbeat,
            request_deserializer=api.HeartbeatRequest.FromString,
            response_serializer=api.HeartbeatResponse.SerializeToString,
        ),
    }
    generic_handler = grpc.method_handlers_generic_handler(
        "tpuslice.SliceRendezvous", rpc_method_handlers
    )
    server.add_generic_rpc_handlers((generic_handler,))
