"""gRPC stubs/servicers for the kubelet deviceplugin v1beta1 API.

Hand-written in the style of grpc_tools output (the build image carries grpcio
but not grpcio-tools).  Method paths must match the kubelet exactly:
/v1beta1.Registration/Register and /v1beta1.DevicePlugin/<RPC>.
"""

import grpc

from . import deviceplugin_pb2 as api


class RegistrationStub:
    """Client to the kubelet's Registration service."""

    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            "/v1beta1.Registration/Register",
            request_serializer=api.RegisterRequest.SerializeToString,
            response_deserializer=api.Empty.FromString,
        )


class RegistrationServicer:
    """Server side of Registration (used by the fake kubelet test harness)."""

    def Register(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


def add_RegistrationServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=api.RegisterRequest.FromString,
            response_serializer=api.Empty.SerializeToString,
        ),
    }
    generic_handler = grpc.method_handlers_generic_handler(
        "v1beta1.Registration", rpc_method_handlers
    )
    server.add_generic_rpc_handlers((generic_handler,))


class DevicePluginStub:
    """Client to a device plugin (used by the fake kubelet test harness)."""

    def __init__(self, channel: grpc.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            "/v1beta1.DevicePlugin/GetDevicePluginOptions",
            request_serializer=api.Empty.SerializeToString,
            response_deserializer=api.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            "/v1beta1.DevicePlugin/ListAndWatch",
            request_serializer=api.Empty.SerializeToString,
            response_deserializer=api.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            "/v1beta1.DevicePlugin/GetPreferredAllocation",
            request_serializer=api.PreferredAllocationRequest.SerializeToString,
            response_deserializer=api.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            "/v1beta1.DevicePlugin/Allocate",
            request_serializer=api.AllocateRequest.SerializeToString,
            response_deserializer=api.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            "/v1beta1.DevicePlugin/PreStartContainer",
            request_serializer=api.PreStartContainerRequest.SerializeToString,
            response_deserializer=api.PreStartContainerResponse.FromString,
        )


class DevicePluginServicer:
    """Server side of DevicePlugin; the plugin adapter subclasses this."""

    def GetDevicePluginOptions(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def ListAndWatch(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def GetPreferredAllocation(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def Allocate(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def PreStartContainer(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


def add_DevicePluginServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=api.Empty.FromString,
            response_serializer=api.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=api.Empty.FromString,
            response_serializer=api.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=api.PreferredAllocationRequest.FromString,
            response_serializer=api.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=api.AllocateRequest.FromString,
            response_serializer=api.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=api.PreStartContainerRequest.FromString,
            response_serializer=api.PreStartContainerResponse.SerializeToString,
        ),
    }
    generic_handler = grpc.method_handlers_generic_handler(
        "v1beta1.DevicePlugin", rpc_method_handlers
    )
    server.add_generic_rpc_handlers((generic_handler,))
