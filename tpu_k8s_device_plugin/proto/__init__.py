"""Protocol stubs: kubelet deviceplugin v1beta1 + tpuhealth.

Message classes are protoc-generated (see gen.sh); the *_pb2_grpc modules are
hand-written in grpc_tools style because the build image has grpcio but not
grpcio-tools.
"""

from . import deviceplugin_pb2
from . import deviceplugin_pb2_grpc
from . import slice_pb2
from . import slice_pb2_grpc
from . import tpuhealth_pb2
from . import tpuhealth_pb2_grpc

__all__ = [
    "deviceplugin_pb2",
    "deviceplugin_pb2_grpc",
    "slice_pb2",
    "slice_pb2_grpc",
    "tpuhealth_pb2",
    "tpuhealth_pb2_grpc",
]
