"""Container-workload device implementation (the KFD-impl analog).

TPU-native analog of AMDGPUKFDImpl
(/root/reference/internal/pkg/amdgpu/amdgpu.go:56-345): discovers chips at
init, precomputes per-resource device lists, answers every kubelet RPC from
memory, and hands containers the allocated /dev/accel* nodes plus the
TPU runtime env (TPU_VISIBLE_CHIPS & friends) — the TPU equivalent of
mounting only the allocated /dev/dri nodes for isolation.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional, TYPE_CHECKING

from tpu_k8s_device_plugin import resilience
from tpu_k8s_device_plugin.allocator import (
    AllocationError,
    devices_from_discovery,
    first_fit,
)
from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
from tpu_k8s_device_plugin.types import DeviceImpl, DevicePluginContext, constants
from . import discovery
from .discovery import TpuDevice
from .topology import IciTopology, derive_worker_identity

if TYPE_CHECKING:  # hints only; slice stays an optional runtime wiring
    from tpu_k8s_device_plugin.slice import SliceClient

log = logging.getLogger(__name__)

# Signature of the granular health overlay (wired to the tpu-metrics-exporter
# client; injected so the impl is testable without a running exporter).
HealthFn = Callable[[], Dict[str, str]]


class TpuContainerImpl(DeviceImpl):
    """DeviceImpl for container workloads via the accel driver."""

    def __init__(
        self,
        resource_naming_strategy: str = constants.RESOURCE_NAMING_STRATEGY_SINGLE,
        sysfs_root: str = "/sys",
        dev_root: str = "/dev",
        tpu_env_path: str = constants.TPU_ENV_FILE,
        health_fn: Optional[HealthFn] = None,
        slice_client: Optional["SliceClient"] = None,
        probe_watchdog_s: float = constants.PROBE_WATCHDOG_TIMEOUT_S,
    ):
        self._strategy = resource_naming_strategy
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root
        self._tpu_env_path = tpu_env_path
        self._health_fn = health_fn
        self._slice = slice_client
        # hung-probe containment: a libtpu/sysfs probe wedged inside a
        # C call (dead NFS stat, stuck driver ioctl) must fail THIS
        # pulse's health refresh, not freeze the pulse loop.  The
        # watchdog abandons the hung call; the breaker stops paying
        # the watchdog timeout once hanging is established; and
        # _probe_wedged turns the trip into an Unhealthy verdict for
        # every advertised device until a probe succeeds again.
        self._probe_watchdog_s = probe_watchdog_s
        self._probe_wedged = False
        self.set_resilience()

        self.chips: Dict[str, TpuDevice] = {}
        self.topology: Optional[IciTopology] = None
        self._homogeneous = True
        self._dev_list: Dict[str, List[pluginapi.Device]] = {}
        self._chips_by_dev_id: Dict[str, TpuDevice] = {}
        # operator-visible fragmentation signal (VERDICT r3 #8): counts
        # Allocates whose chip set was non-contiguous on the ICI grid and
        # got linear N,1,1 bounds — those pods see degraded collectives
        self._counters_lock = threading.Lock()
        self._degraded_bounds = 0

        self._init()

    # -- init (≈ AMDGPUKFDImpl.Init, amdgpu.go:68-88) -----------------------

    def _init(self) -> None:
        self._apply_discovery(*self._discover())

    def _discover(self):
        """Run discovery and validate the result (raises on an unusable
        host).  Shared by init and runtime rediscovery."""
        accel_dir = os.path.join(self._sysfs_root, "class", "accel")
        if not os.path.isdir(accel_dir):
            raise RuntimeError("no TPU accel driver loaded")
        chips, topology = discovery.get_tpu_chips(
            self._sysfs_root, self._dev_root, self._tpu_env_path
        )
        # The container path serves chips through the accel driver only; a
        # chip discovered via the raw PCI fallback (accel_index -1) has no
        # /dev/accelN node to mount — advertising it would admit pods that
        # get zero usable TPUs.  (Such chips belong to the vf/pf impls.)
        chips = {cid: c for cid, c in chips.items() if c.accel_index >= 0}
        if not chips:
            raise RuntimeError("accel class present but no TPU chips found")
        homogeneous = discovery.is_homogeneous(chips)
        if (
            not homogeneous
            and self._strategy == constants.RESOURCE_NAMING_STRATEGY_SINGLE
        ):
            raise RuntimeError(
                "chips with different partition modes on one node require "
                "resource_naming_strategy=mixed"
            )
        return chips, topology, homogeneous

    def _apply_discovery(self, chips, topology, homogeneous) -> None:
        """Swap in a discovery result.  Builds the fresh lookup maps first
        and assigns _chips_by_dev_id before _dev_list: concurrent gRPC
        handlers iterate _dev_list and index into _chips_by_dev_id, so the
        id map must never lag the device list."""
        self.chips = chips
        self.topology = topology
        self._homogeneous = homogeneous
        by_dev_id: Dict[str, TpuDevice] = {}
        dev_list: Dict[str, List[pluginapi.Device]] = {}
        for resource in self.get_resource_names():
            dev_list[resource] = self._plugin_device_list(resource, by_dev_id)
        self._chips_by_dev_id = by_dev_id
        self._dev_list = dev_list

    @staticmethod
    def _discovery_signature(chips, topology):
        """Comparable fingerprint of what the node advertises."""
        return (
            tuple(sorted(
                (c.id, c.accel_index, c.partition_mode, c.coords)
                for c in chips.values()
            )),
            topology.topology_str if topology else "",
        )

    def rediscover(self) -> bool:
        """Pulse-driven re-enumeration (VERDICT r1 #2: a partition-mode
        change must not require a pod restart).  Keeps the last good state
        when the host becomes transiently unusable — the simple health
        check demotes the node in that case instead."""
        try:
            chips, topology, homogeneous = self._discover()
        except RuntimeError as e:
            log.warning("rediscovery failed; keeping current state: %s", e)
            return False
        if (self._discovery_signature(chips, topology)
                == self._discovery_signature(self.chips, self.topology)):
            return False
        log.info(
            "hardware changed: %d chip(s), partition modes %s",
            len(chips), sorted({c.partition_mode for c in chips.values()}),
        )
        self._apply_discovery(chips, topology, homogeneous)
        return True

    # -- resource naming (≈ GetResourceNames, amdgpu.go:122-162) ------------

    def get_resource_names(self) -> List[str]:
        if not self.chips:
            return []
        counts = discovery.unique_partition_config_count(self.chips)
        if self._homogeneous:
            if self._strategy == constants.RESOURCE_NAMING_STRATEGY_SINGLE:
                return [constants.DEVICE_TYPE_TPU]
            # mixed on a homogeneous node: partition-typed names, falling
            # back to plain "tpu" when partitioning isn't in play
            if counts == {constants.DEVICE_TYPE_TPU: len(self.chips)}:
                return [constants.DEVICE_TYPE_TPU]
            return sorted(r for r, c in counts.items() if c > 0)
        return sorted(r for r, c in counts.items() if c > 0)

    def _alloc_devices_for(self, resource: str):
        partitioned = resource == constants.DEVICE_TYPE_TPU_CORE
        if self._homogeneous:
            return devices_from_discovery(self.chips)
        return devices_from_discovery(self.chips, partitioned=partitioned)

    def _plugin_device_list(
        self, resource: str, by_dev_id: Dict[str, TpuDevice]
    ) -> List[pluginapi.Device]:
        devs = []
        for ad in self._alloc_devices_for(resource):
            chip = self.chips[ad.parent_id]
            by_dev_id[ad.id] = chip
            devs.append(
                pluginapi.Device(
                    ID=ad.id,
                    health=constants.HEALTHY,
                    topology=pluginapi.TopologyInfo(
                        nodes=[pluginapi.NUMANode(ID=chip.numa_node)]
                    ),
                )
            )
        return devs

    # -- DeviceImpl RPC surface ---------------------------------------------

    def start(self, ctx: DevicePluginContext) -> None:
        """Initialise this resource's allocator (≈ Start, amdgpu.go:90-119).
        Allocator failure degrades to kubelet-default allocation."""
        policy = ctx.get_allocator()
        if policy is None:
            ctx.set_allocator_error(True)
            return
        try:
            policy.init(self._alloc_devices_for(ctx.resource_name()), self.topology)
            # start() re-runs after runtime rediscovery: a successful
            # re-init must clear a previous sticky failure
            ctx.set_allocator_error(False)
        except AllocationError as e:
            log.error(
                "allocator init failed for %s; falling back to kubelet "
                "default allocation: %s", ctx.resource_name(), e,
            )
            ctx.set_allocator_error(True)

    def get_options(self, ctx: DevicePluginContext) -> pluginapi.DevicePluginOptions:
        if ctx.get_allocator_error():
            return pluginapi.DevicePluginOptions()
        return pluginapi.DevicePluginOptions(get_preferred_allocation_available=True)

    def enumerate(self, ctx: DevicePluginContext) -> List[pluginapi.Device]:
        return list(self._dev_list.get(ctx.resource_name(), []))

    def allocate(
        self, ctx: DevicePluginContext, req: pluginapi.AllocateRequest
    ) -> pluginapi.AllocateResponse:
        """Device nodes + TPU runtime env for each container
        (≈ Allocate, amdgpu.go:255-297; pure map lookups, no sysfs I/O)."""
        resp = pluginapi.AllocateResponse()
        for creq in req.container_requests:
            car = resp.container_responses.add()
            chips: List[TpuDevice] = []
            core_ids: List[str] = []
            for dev_id in creq.devices_ids:
                chip = self._chips_by_dev_id.get(dev_id)
                if chip is None:
                    raise RuntimeError(f"allocate for unknown device {dev_id}")
                if chip not in chips:
                    chips.append(chip)
                if "#core" in dev_id:
                    core_ids.append(dev_id)
            for chip in chips:
                if chip.accel_index < 0:
                    continue
                spec = car.devices.add()
                spec.host_path = chip.dev_path
                spec.container_path = chip.dev_path
                spec.permissions = "rw"
            self._populate_env(car, chips, core_ids)
        return resp

    def _populate_env(self, car, chips: List[TpuDevice], core_ids: List[str]):
        """TPU runtime env: restrict libtpu to the allocated chips.  This is
        the isolation mechanism — libtpu grabs every local chip unless
        TPU_VISIBLE_CHIPS narrows it (SURVEY §7 'per-container chip
        isolation')."""
        visible = ",".join(
            str(c.accel_index) for c in chips if c.accel_index >= 0
        )
        car.envs[constants.ENV_TPU_VISIBLE_CHIPS] = visible
        car.envs[constants.ENV_TPU_SKIP_MDS_QUERY] = "true"
        topo = self.topology
        if topo is None or not chips:
            return
        full_host = len({c.id for c in chips}) == len(self.chips)
        if full_host:
            # Whole host allocated: the pod is (potentially) one worker of a
            # multi-host slice — propagate the slice-level identity so JAX /
            # libtpu can initialise distributed training across hosts.
            if topo.accelerator_type:
                car.envs[constants.ENV_TPU_ACCELERATOR_TYPE] = topo.accelerator_type
            car.envs[constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS] = ",".join(
                str(b) for b in topo.chips_per_host_bounds
            )
            car.envs[constants.ENV_TPU_PROCESS_BOUNDS] = ",".join(
                str(b) for b in topo.host_bounds
            )
            slice_env = self._slice.slice_env() if self._slice else {}
            membership = self._slice.membership if self._slice else None
            wid, _ = derive_worker_identity(
                topo,
                full_host=True,
                slice_rank=self._slice.rank if self._slice else None,
                slice_workers=membership.num_workers if membership else 0,
            )
            car.envs[constants.ENV_TPU_WORKER_ID] = str(wid)
            car.envs[constants.ENV_TPU_TOPOLOGY] = topo.topology_str
            # Rendezvous-agreed contract: identical on every member of the
            # slice (modulo rank), so coordinated containers never depend
            # on per-host metadata guesses.  Includes TPU_WORKER_ID=rank,
            # consistent with the derivation above.
            for key, val in slice_env.items():
                car.envs[key] = val
        else:
            # Sub-host allocation: a standalone single-process slice.  The
            # slice-wide accelerator type would mislead libtpu (it implies a
            # chip count we are not granting), so it is deliberately omitted.
            bounds, degraded = _bounds_of(chips, topo)
            if degraded:
                with self._counters_lock:
                    self._degraded_bounds += 1
                log.warning(
                    "non-contiguous allocation %s (coords %s): degrading "
                    "to linear bounds %s — this pod's ICI collectives "
                    "will be slow; node is fragmented",
                    [c.id for c in chips],
                    [c.coords for c in chips],
                    bounds,
                )
            car.envs[constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS] = bounds
            car.envs[constants.ENV_TPU_PROCESS_BOUNDS] = "1,1,1"
            # standalone single-process view: worker 0 of 1 by derivation,
            # not by hardcoded string — same helper as the full-host path
            wid, _ = derive_worker_identity(topo, full_host=False)
            car.envs[constants.ENV_TPU_WORKER_ID] = str(wid)
        if core_ids:
            # per-core partitions: tell the runtime which TensorCores of the
            # visible chips belong to this container
            car.envs["TPU_VISIBLE_CORES"] = ",".join(
                i.split("#core", 1)[1] for i in sorted(core_ids)
            )

    def get_preferred_allocation(
        self, ctx: DevicePluginContext, req: pluginapi.PreferredAllocationRequest
    ) -> pluginapi.PreferredAllocationResponse:
        resp = pluginapi.PreferredAllocationResponse()
        policy = ctx.get_allocator()
        for creq in req.container_requests:
            if policy is None or ctx.get_allocator_error():
                # no policy / failed init is a supported degraded state
                # (see start()): answer first-fit like the kubelet would
                ids = first_fit(
                    list(creq.available_deviceIDs),
                    list(creq.must_include_deviceIDs),
                    int(creq.allocation_size),
                )
            else:
                ids = policy.allocate(
                    list(creq.available_deviceIDs),
                    list(creq.must_include_deviceIDs),
                    int(creq.allocation_size),
                )
            resp.container_responses.add(deviceIDs=ids)
        return resp

    def counters(self) -> Dict[str, int]:
        """Impl-level counters for the debug/metrics surface."""
        with self._counters_lock:
            return {"degraded_bounds_allocations": self._degraded_bounds}

    # -- health (≈ UpdateHealth + simpleHealthCheck, amdgpu.go:322-345,
    #    865-910, exporter overlay :954-974) --------------------------------

    def set_slice_client(self, client: Optional["SliceClient"]) -> None:
        """Late wiring: the client needs this impl's chip inventory and
        local_health before it can be built, so cmd attaches it after
        construction."""
        self._slice = client

    def set_resilience(self, metrics=None, recorder=None) -> None:
        """(Re)build the probe watchdog + breaker, optionally wired to
        an obs registry's resilience families and the flight recorder
        (the PluginManager calls this with its own pair)."""
        self._probe_watchdog = resilience.Watchdog(
            "probe", self._probe_watchdog_s,
            metrics=metrics, recorder=recorder, logger=log)
        self._probe_breaker = resilience.CircuitBreaker(
            "probe", failure_threshold=3,
            reset_timeout_s=self._probe_watchdog_s * 3,
            metrics=metrics, recorder=recorder, logger=log)

    def _granular_health(self) -> Dict[str, str]:
        """Per-chip health overlay (exporter-fed sysfs chip_state watch);
        {} when the probe is unwired or failing.

        A probe that HANGS (vs fails fast) is a different beast: the
        watchdog abandons it after ``probe_watchdog_s`` and the impl
        flips ``_probe_wedged`` — update_health then demotes every
        device, because a wedged probe usually means the driver/bus
        under the chips is wedged too and we can no longer vouch for
        them.  Fast failures keep today's semantics (fall back to the
        simple node check).  The breaker stops a persistently-hanging
        probe from costing one watchdog timeout per health call."""
        if self._health_fn is None:
            return {}
        try:
            out = self._probe_breaker.call(
                lambda: self._probe_watchdog.call(self._health_fn))
        except resilience.WatchdogTimeout:
            self._probe_wedged = True
            return {}
        except resilience.CircuitOpenError:
            # breaker open: skip the probe, keep the standing verdict
            # (wedged stays wedged until a successful probe clears it)
            return {}
        except Exception as e:
            log.warning("granular health probe failed: %s", e)
            return {}
        self._probe_wedged = False
        return out

    def local_health(self) -> "tuple[bool, str]":
        """This host's contribution to slice-wide health — what the slice
        client reports in every heartbeat.  A single wedged chip makes the
        whole HOST unhealthy here, and the coordinator fans that out to
        the whole SLICE."""
        if not self.simple_health_check():
            return False, "node health probe failed"
        per_chip = self._granular_health()
        if self._probe_wedged:
            return False, "health probe hung (watchdog abandoned it)"
        bad = sorted(
            cid for cid in self.chips
            if per_chip.get(cid, constants.HEALTHY) != constants.HEALTHY
        )
        if bad:
            return False, "unhealthy chips: " + ",".join(bad)
        return True, ""

    def simple_health_check(self) -> bool:
        """Cheap whole-node probe: the accel class still enumerates every
        chip we advertised and the device nodes exist."""
        found = {idx for idx, _ in discovery.list_accel_nodes(self._sysfs_root)}
        for chip in self.chips.values():
            if chip.accel_index not in found:
                return False
            if chip.dev_path and not os.path.exists(chip.dev_path):
                return False
        return True

    def update_health(self, ctx: DevicePluginContext) -> List[pluginapi.Device]:
        node_health = (
            constants.HEALTHY if self.simple_health_check() else constants.UNHEALTHY
        )
        per_chip: Dict[str, str] = self._granular_health()
        if self._probe_wedged:
            # a hung probe means nothing can vouch for the chips; the
            # watchdog already failed the call, so this frame (within
            # ONE pulse of the hang) demotes everything rather than
            # advertising capacity on a wedged bus
            node_health = constants.UNHEALTHY
            per_chip = {}
        # Slice-wide verdict: ANY member's wedged chip (or a silent member)
        # poisons the ICI collectives of every host, so a slice-Unhealthy
        # verdict demotes every local device — the kubelet then stops
        # scheduling onto any member until the slice recovers.  The same
        # channel propagates recovery.
        slice_down = False
        overlay = self._slice.health_overlay() if self._slice else None
        if overlay is not None:
            slice_ok, bad_hosts = overlay
            if not slice_ok:
                slice_down = True
                log.debug("slice unhealthy (members: %s); demoting all "
                          "local devices", bad_hosts)
        # fresh messages, not in-place mutation: the cached _dev_list entries
        # are shared with every open ListAndWatch stream, and concurrent
        # health writes would race with their serialization
        out: List[pluginapi.Device] = []
        for dev in self._dev_list.get(ctx.resource_name(), []):
            # .get(): a rediscovery swap can land between our _dev_list read
            # and this lookup, leaving dev.ID unknown to the new map — fall
            # back to node health for that one frame (the post-swap beat
            # resends the fresh list immediately after)
            chip = self._chips_by_dev_id.get(dev.ID)
            fresh = pluginapi.Device()
            fresh.CopyFrom(dev)
            if slice_down:
                fresh.health = constants.UNHEALTHY
            else:
                fresh.health = (
                    per_chip.get(chip.id, node_health) if chip else node_health
                )
            out.append(fresh)
        return out


def _bounds_of(chips: List[TpuDevice], topo: IciTopology) -> "tuple[str, bool]":
    """Bounding box of the allocated chips on the host grid, as the
    TPU_CHIPS_PER_HOST_BOUNDS value for the container.

    When the set is non-contiguous (kubelet default allocation under
    fragmentation), the box volume would exceed the chip count and libtpu's
    bounds/chip-count consistency check would fail — degrade to a linear
    shape instead.  Returns (bounds, degraded) so the caller can surface
    the lossy fallback (warning log + counter)."""
    xs = [c.coords[0] for c in chips]
    ys = [c.coords[1] for c in chips]
    zs = [c.coords[2] for c in chips]
    w = max(xs) - min(xs) + 1
    h = max(ys) - min(ys) + 1
    d = max(zs) - min(zs) + 1
    if w * h * d != len(chips):
        return f"{len(chips)},1,1", True
    return f"{w},{h},{d}", False
