"""TPU chip discovery and device implementations.

TPU-native analog of the reference's ``internal/pkg/amdgpu`` package
(/root/reference/internal/pkg/amdgpu/): where AMD discovers GPUs through the
KFD/amdgpu sysfs trees and libdrm ioctls, this package discovers TPU chips
through the Linux ``accel`` class + PCI sysfs, the host ``tpu-env`` metadata
file, and (optionally) the native tpuprobe shim.
"""

from .topology import (
    AcceleratorSpec,
    IciTopology,
    parse_accelerator_type,
    read_tpu_env,
)
from .discovery import TpuDevice, get_tpu_chips, is_homogeneous, unique_partition_config_count

__all__ = [
    "AcceleratorSpec",
    "IciTopology",
    "TpuDevice",
    "get_tpu_chips",
    "is_homogeneous",
    "parse_accelerator_type",
    "read_tpu_env",
    "unique_partition_config_count",
]
