"""TPU chip discovery from sysfs + tpu-env metadata.

TPU-native analog of GetAMDGPUs and friends
(/root/reference/internal/pkg/amdgpu/amdgpu.go:448-568): where AMD walks
``/sys/module/amdgpu/drivers/pci:amdgpu`` and the KFD topology tree, this
walks the Linux ``accel`` class (one entry per TPU chip, ``device`` symlink
into the PCI tree) with a raw PCI-bus fallback, and reads ICI topology from
the tpu-env metadata file.  Every entry point takes injectable roots so the
test suite can run against captured fixture trees under ``testdata/``
(the reference's central testing trick, SURVEY.md §4).
"""

from __future__ import annotations

import glob
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpu_k8s_device_plugin.types import constants
from . import sysfs
from .topology import (
    IciTopology,
    partition_modes_from_env,
    read_tpu_env,
    topology_from_env,
)

log = logging.getLogger(__name__)


@dataclass
class TpuDevice:
    """One discovered TPU chip (typed; the reference uses an untyped
    map[string]interface{} bag, amdgpu.go:516 — SURVEY flags that as a
    thing not to copy)."""

    id: str                       # stable device id = PCI DBDF, e.g. "0000:00:04.0"
    accel_index: int              # N in /dev/accelN, -1 if not bound
    pci_address: str
    vendor_id: str = ""
    device_id: str = ""           # PCI device id, e.g. "0x0062"
    numa_node: int = 0
    coords: Tuple[int, int, int] = (0, 0, 0)   # local ICI grid coords
    cores_per_chip: int = 1
    partition_mode: str = "chip"  # "chip" | "core"
    dev_path: str = ""            # /dev/accelN
    iommu_group: str = ""         # for vfio paths

    @property
    def partition_type(self) -> str:
        """Resource-type key for mixed naming (≈ computePartitionType +
        memoryPartitionType concatenation, amdgpu.go:228)."""
        return (
            constants.DEVICE_TYPE_TPU
            if self.partition_mode == "chip"
            else constants.DEVICE_TYPE_TPU_CORE
        )


def list_accel_nodes(sysfs_root: str = "/sys") -> List[Tuple[int, str]]:
    """Enumerate accel class entries → [(accel_index, pci_device_dir)].

    Follows each ``/sys/class/accel/accelN/device`` symlink to the backing
    PCI device directory (≈ the reference following drivers/pci:amdgpu link
    targets, amdgpu.go:448-462).
    """
    out: List[Tuple[int, str]] = []
    class_dir = os.path.join(sysfs_root, "class", "accel")
    for entry in sorted(glob.glob(os.path.join(class_dir, "accel[0-9]*"))):
        m = re.search(r"accel(\d+)$", entry)
        if not m:
            continue
        dev_link = os.path.join(entry, "device")
        if not os.path.exists(dev_link):
            continue
        out.append((int(m.group(1)), os.path.realpath(dev_link)))
    return out


def list_tpu_pci_devices(sysfs_root: str = "/sys") -> List[str]:
    """Fallback enumeration: PCI devices with the Google vendor id
    (≈ the reference's /sys/bus/pci scan in the VF/PF impls)."""
    out = []
    pci_dir = os.path.join(sysfs_root, "bus", "pci", "devices")
    for entry in sorted(glob.glob(os.path.join(pci_dir, "*"))):
        if sysfs.read_file(os.path.join(entry, "vendor")) == constants.GOOGLE_VENDOR_ID:
            out.append(os.path.realpath(entry))
    return out


def _pci_addr_of(pci_dir: str) -> str:
    return os.path.basename(pci_dir.rstrip("/"))


def get_tpu_chips(
    sysfs_root: str = "/sys",
    dev_root: str = "/dev",
    tpu_env_path: str = constants.TPU_ENV_FILE,
) -> Tuple[Dict[str, TpuDevice], IciTopology]:
    """Discover all local TPU chips and the host's ICI topology.

    Returns ({device_id: TpuDevice}, IciTopology).  Everything downstream
    (Enumerate/Allocate/health) works off this precomputed map — the
    precompute-at-init shape the reference relies on for microsecond
    Allocate latency (SURVEY.md §3.3).
    """
    devices: Dict[str, TpuDevice] = {}

    accel_nodes = list_accel_nodes(sysfs_root)
    pci_dirs: List[Tuple[int, str]]
    if accel_nodes:
        pci_dirs = accel_nodes
    else:
        # No accel class (older driver or passthrough host): the chips are
        # not bound to the accel driver, so there is no accelN index and no
        # /dev/accelN node — honour TpuDevice's "-1 if not bound" contract;
        # passthrough consumers address chips via vfio instead.
        pci_dirs = [(-1, p) for p in list_tpu_pci_devices(sysfs_root)]

    for accel_index, pci_dir in pci_dirs:
        vendor = sysfs.read_file(os.path.join(pci_dir, "vendor"))
        if vendor and vendor != constants.GOOGLE_VENDOR_ID:
            log.warning("accel%d at %s has non-TPU vendor %s; skipping",
                        accel_index, pci_dir, vendor)
            continue
        pci_addr = _pci_addr_of(pci_dir)
        dev_path = (
            os.path.join(dev_root, f"accel{accel_index}")
            if accel_index >= 0
            else ""
        )
        dev = TpuDevice(
            id=pci_addr,
            accel_index=accel_index,
            pci_address=pci_addr,
            vendor_id=vendor or constants.GOOGLE_VENDOR_ID,
            device_id=sysfs.read_file(os.path.join(pci_dir, "device")),
            numa_node=sysfs.numa_node(pci_dir),
            dev_path=dev_path,
        )
        dev.iommu_group = sysfs.iommu_group(pci_dir)
        devices[dev.id] = dev

    env = read_tpu_env(tpu_env_path)
    sample_devid = next(iter(devices.values())).device_id if devices else ""
    topo = topology_from_env(env, fallback_chip_count=len(devices),
                             pci_device_id=sample_devid)

    # Assign local grid coordinates by accel index order (the TPU runtime's
    # chip numbering is x-fastest over the host grid) and per-chip partition
    # modes from the metadata.  Unbound chips (accel_index -1) order by PCI
    # address, which scans in the same physical order.
    ordered = sorted(
        devices.values(), key=lambda d: (d.accel_index < 0, d.accel_index, d.id)
    )
    modes = partition_modes_from_env(env, len(ordered))
    cores = topo.spec.cores_per_chip if topo.spec else 1
    for i, dev in enumerate(ordered):
        dev.coords = topo.chip_coords(i)
        dev.cores_per_chip = cores
        dev.partition_mode = modes[i] if cores > 1 else "chip"

    return devices, topo


def is_homogeneous(devices: Dict[str, TpuDevice]) -> bool:
    """True when every chip has the same partition granularity
    (≈ IsHomogeneous over partition styles, amdgpu.go:570-592)."""
    modes = {d.partition_mode for d in devices.values()}
    return len(modes) <= 1


def unique_partition_config_count(devices: Dict[str, TpuDevice]) -> Dict[str, int]:
    """Device count per partition-type resource name
    (≈ UniquePartitionConfigCount, amdgpu.go — drives mixed naming)."""
    out: Dict[str, int] = {}
    for d in devices.values():
        out[d.partition_type] = out.get(d.partition_type, 0) + 1
    return out


# ---------------------------------------------------------------------------
# Version probing for the labeller (≈ GetFirmwareVersions via libdrm ioctls,
# amdgpu.go:691-736, and driver version from /sys/module, labeller
# main.go:166-236).  The TPU driver exposes these through sysfs/module files;
# the native tpuprobe shim supplements with a device-open probe.
# ---------------------------------------------------------------------------

TPU_DRIVER_MODULE_CANDIDATES = ("tpu", "tpu_common", "accel", "google_tpu")


def get_driver_versions(sysfs_root: str = "/sys") -> Dict[str, str]:
    """Best-effort TPU driver version/srcversion from /sys/module."""
    out: Dict[str, str] = {}
    for mod in TPU_DRIVER_MODULE_CANDIDATES:
        base = os.path.join(sysfs_root, "module", mod)
        if not os.path.isdir(base):
            continue
        ver = sysfs.read_file(os.path.join(base, "version"))
        src = sysfs.read_file(os.path.join(base, "srcversion"))
        if ver:
            out["driver-version"] = ver
        if src:
            out["driver-src-version"] = src
        if out:
            break
    return out


def get_firmware_version(pci_dir_or_sysfs_root: str, accel_index: int = -1) -> str:
    """Firmware version for a chip, from the accel class attrs when present."""
    if accel_index >= 0:
        path = os.path.join(
            pci_dir_or_sysfs_root, "class", "accel", f"accel{accel_index}",
            "device", "firmware_version",
        )
    else:
        path = os.path.join(pci_dir_or_sysfs_root, "firmware_version")
    return sysfs.read_file(path)
