"""VM-passthrough device implementations (VF and PF).

TPU-native analogs of AMDGPUVFImpl and AMDGPUPFImpl
(/root/reference/internal/pkg/amdgpu/amdgpu_sriov.go:55-308,
amdgpu_pf.go:51-229): devices are keyed by IOMMU group, allocation mounts
/dev/vfio/<group> + /dev/vfio/vfio and announces the passthrough PCI
addresses via PCI_RESOURCE_GOOGLE_COM_<RESOURCE> env for the virt-launcher.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, List, Optional

from tpu_k8s_device_plugin.allocator import first_fit
from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
from tpu_k8s_device_plugin.types import DeviceImpl, DevicePluginContext, constants
from . import vfio

log = logging.getLogger(__name__)

HealthFn = Callable[[], Dict[str, str]]


class _VfioImplBase(DeviceImpl):
    """Shared VFIO allocation/enumeration shape for VF and PF impls."""

    resource_single = constants.DEVICE_TYPE_TPU
    resource_mixed = constants.DEVICE_TYPE_TPU

    def __init__(
        self,
        resource_naming_strategy: str = constants.RESOURCE_NAMING_STRATEGY_SINGLE,
        sysfs_root: str = "/sys",
        dev_root: str = "/dev",
        health_fn: Optional[HealthFn] = None,
    ):
        self._strategy = resource_naming_strategy
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root
        self._health_fn = health_fn
        # iommu group -> pci address of the passthrough function
        self._group_to_pci: Dict[str, str] = {}
        self._numa: Dict[str, int] = {}
        self._discover()
        if not self._group_to_pci:
            raise RuntimeError(f"no devices found for {type(self).__name__}")

    def _discover(self) -> None:
        raise NotImplementedError

    # -- DeviceImpl ---------------------------------------------------------

    def start(self, ctx: DevicePluginContext) -> None:
        # VFIO passthrough has no topology-aware allocator: VMs take whole
        # functions; kubelet-default selection is fine (matches reference,
        # which only wires the best-effort policy into the KFD impl).
        ctx.set_allocator_error(True)

    def get_resource_names(self) -> List[str]:
        if self._strategy == constants.RESOURCE_NAMING_STRATEGY_MIXED:
            return [self.resource_mixed]
        return [self.resource_single]

    def get_options(self, ctx: DevicePluginContext) -> pluginapi.DevicePluginOptions:
        return pluginapi.DevicePluginOptions()

    def enumerate(self, ctx: DevicePluginContext) -> List[pluginapi.Device]:
        return [
            pluginapi.Device(
                ID=group,
                health=constants.HEALTHY,
                topology=pluginapi.TopologyInfo(
                    nodes=[pluginapi.NUMANode(ID=self._numa.get(group, 0))]
                ),
            )
            for group in sorted(self._group_to_pci, key=_group_key)
        ]

    def allocate(
        self, ctx: DevicePluginContext, req: pluginapi.AllocateRequest
    ) -> pluginapi.AllocateResponse:
        """Mount the VFIO group nodes and announce PCI addresses
        (≈ amdgpu_sriov.go:150-204, amdgpu_pf.go:146-197)."""
        resp = pluginapi.AllocateResponse()
        vfio_dir = os.path.join(self._dev_root, "vfio")
        for creq in req.container_requests:
            car = resp.container_responses.add()
            pci_addrs = []
            for group in creq.devices_ids:
                pci = self._group_to_pci.get(group)
                if pci is None:
                    raise RuntimeError(f"allocate for unknown IOMMU group {group}")
                pci_addrs.append(pci)
                spec = car.devices.add()
                spec.host_path = os.path.join(vfio_dir, group)
                spec.container_path = os.path.join(vfio_dir, group)
                spec.permissions = "rw"
            # the VFIO container node, once per container
            spec = car.devices.add()
            spec.host_path = os.path.join(vfio_dir, "vfio")
            spec.container_path = os.path.join(vfio_dir, "vfio")
            spec.permissions = "rw"
            res_suffix = ctx.resource_name().upper().replace("-", "_")
            car.envs[f"{constants.PCI_TPU_PREFIX}_{res_suffix}"] = ",".join(
                pci_addrs
            )
        return resp

    def get_preferred_allocation(
        self, ctx: DevicePluginContext, req: pluginapi.PreferredAllocationRequest
    ) -> pluginapi.PreferredAllocationResponse:
        # Not advertised in options; kubelet shouldn't call it.  Answer
        # defensively with first-fit.
        resp = pluginapi.PreferredAllocationResponse()
        for creq in req.container_requests:
            ids = first_fit(
                list(creq.available_deviceIDs),
                list(creq.must_include_deviceIDs),
                int(creq.allocation_size),
            )
            resp.container_responses.add(deviceIDs=ids)
        return resp

    def update_health(self, ctx: DevicePluginContext) -> List[pluginapi.Device]:
        devs = self.enumerate(ctx)
        node_health = (
            constants.HEALTHY if self._node_healthy() else constants.UNHEALTHY
        )
        per_func: Dict[str, str] = {}
        if self._health_fn is not None:
            try:
                per_func = self._health_fn()
            except Exception as e:
                log.warning("granular health probe failed: %s", e)
        for dev in devs:
            dev.health = per_func.get(self._health_key(dev.ID), node_health)
        return devs

    def _health_key(self, dev_id: str) -> str:
        """PCI address the health map is keyed by for this device."""
        return self._group_to_pci.get(dev_id, "")

    def _node_healthy(self) -> bool:
        raise NotImplementedError


def _group_key(group: str):
    try:
        return (0, int(group))
    except ValueError:
        return (1, group)


class TpuVfImpl(_VfioImplBase):
    """SR-IOV virtual functions for TPU VMs (≈ AMDGPUVFImpl).  Health of a
    VF maps from its parent PF's health (amdgpu_sriov.go:217-308)."""

    resource_single = constants.DEVICE_TYPE_TPU
    resource_mixed = constants.DEVICE_TYPE_TPU_VF

    def _discover(self) -> None:
        self._vf_mapping = vfio.get_vf_mapping(self._sysfs_root)
        for group, info in self._vf_mapping.items():
            self._group_to_pci[group] = info.pci_address
            self._numa[group] = info.numa_node

    def _node_healthy(self) -> bool:
        return os.path.isdir(
            os.path.join(
                self._sysfs_root, "bus", "pci", "drivers",
                constants.TPU_VF_DRIVER_NAME,
            )
        )

    def _health_key(self, dev_id: str) -> str:
        # a VF inherits its parent PF's health (amdgpu_sriov.go:217-308)
        info = self._vf_mapping.get(dev_id)
        return info.pf_pci_address if info else ""


class TpuPfImpl(_VfioImplBase):
    """Whole-function passthrough via vfio-pci (≈ AMDGPUPFImpl).  Node
    health is the presence of the vfio-pci driver (amdgpu_pf.go:210-229)."""

    resource_single = constants.DEVICE_TYPE_TPU
    resource_mixed = constants.DEVICE_TYPE_TPU_PF

    def _discover(self) -> None:
        for group, info in vfio.get_pf_mapping(self._sysfs_root).items():
            self._group_to_pci[group] = info.pci_address
            self._numa[group] = info.numa_node

    def _node_healthy(self) -> bool:
        return os.path.isdir(
            os.path.join(
                self._sysfs_root, "bus", "pci", "drivers",
                constants.VFIO_DRIVER_NAME,
            )
        )
