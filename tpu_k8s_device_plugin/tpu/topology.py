"""ICI topology model: tpu-env parsing, accelerator-type table, chip coordinates.

The reference reads per-link topology from the KFD sysfs tree
(/root/reference/internal/pkg/amdgpu/amdgpu.go:406-445,821-863 and
allocator/device.go:159-218).  TPU hosts have no KFD analog: the ICI mesh is
described indirectly by the host metadata the TPU runtime publishes (the GCE
metadata server's ``tpu-env`` attribute, mirrored to a host file by the VM
runtime / GKE).  This module turns that metadata into explicit chip grid
coordinates, which drive both the allocator's ICI-distance weights and the
node labeller's topology labels.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpu_k8s_device_plugin.types import constants


@dataclass(frozen=True)
class AcceleratorSpec:
    """Static per-generation chip properties."""

    generation: str          # "v4", "v5e", "v5p", "v6e", ...
    product_name: str        # marketing name for the labeller
    cores_per_chip: int      # TensorCores per chip (1 on v5e/v6e, 2 on v4/v5p)
    hbm_bytes_per_chip: int
    default_chips_per_host: Tuple[int, int, int]
    torus_3d: bool           # 3D torus ICI (v4/v5p) vs 2D mesh (v5e/v6e)
    peak_bf16_flops: int = 0  # published per-chip bf16 peak (MFU denominator)


_GIB = 1024 ** 3
_TFLOPS = 10 ** 12

# Keyed by the accelerator-type prefix used in ACCELERATOR_TYPE strings
# (e.g. "v5litepod-8" → prefix "v5litepod").  Peak bf16 FLOP/s are the
# published per-chip figures (v2/v3 predate bf16 marketing splits; their
# listed peak is used).
ACCELERATOR_SPECS: Dict[str, AcceleratorSpec] = {
    "v2": AcceleratorSpec("v2", "TPU v2", 2, 8 * _GIB, (2, 2, 1), False,
                          45 * _TFLOPS),
    "v3": AcceleratorSpec("v3", "TPU v3", 2, 16 * _GIB, (2, 2, 1), False,
                          123 * _TFLOPS),
    "v4": AcceleratorSpec("v4", "TPU v4", 2, 32 * _GIB, (2, 2, 1), True,
                          275 * _TFLOPS),
    "v5litepod": AcceleratorSpec("v5e", "TPU v5e", 1, 16 * _GIB, (2, 4, 1),
                                 False, 197 * _TFLOPS),
    "v5p": AcceleratorSpec("v5p", "TPU v5p", 2, 95 * _GIB, (2, 2, 1), True,
                           459 * _TFLOPS),
    "v6e": AcceleratorSpec("v6e", "TPU v6e (Trillium)", 1, 32 * _GIB,
                           (2, 4, 1), False, 918 * _TFLOPS),
}


def spec_for_device_kind(device_kind: str) -> Optional[AcceleratorSpec]:
    """Map a jax Device.device_kind string (e.g. "TPU v5 lite", "TPU v4")
    onto the spec table — how the bench finds its MFU denominator on the
    real chip, where no tpu-env fixture is in play."""
    kind = device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return ACCELERATOR_SPECS["v5litepod"]
    if "v6" in kind or "trillium" in kind:
        return ACCELERATOR_SPECS["v6e"]
    for prefix in ("v5p", "v4", "v3", "v2"):
        if prefix in kind:
            return ACCELERATOR_SPECS[prefix]
    if "v5" in kind:
        # libtpu reports plain "TPU v5" for v5p (the lite variant always
        # carries "lite"); without this fallback v5p hosts get no MFU
        return ACCELERATOR_SPECS["v5p"]
    return None

# PCI device id → accelerator-type prefix, for sysfs-only fallback when no
# tpu-env metadata is present (≈ the reference's AMDGPU_FAMILY_* table read
# via libdrm ioctls, amdgpu.go:349-404).
PCI_DEVICE_TO_PREFIX = {
    "0x0027": "v3",
    "0x005e": "v4",
    "0x0062": "v5litepod",
    "0x0063": "v5p",
    "0x006f": "v6e",
}


def parse_accelerator_type(accel_type: str) -> Tuple[AcceleratorSpec, int]:
    """Split an ACCELERATOR_TYPE string like ``v5litepod-16`` into
    (generation spec, total chip count in the slice)."""
    m = re.fullmatch(r"([a-z0-9]+)-(\d+)", accel_type.strip())
    if not m:
        raise ValueError(f"unparseable accelerator type: {accel_type!r}")
    prefix, count = m.group(1), int(m.group(2))
    if prefix not in ACCELERATOR_SPECS:
        raise ValueError(f"unknown accelerator generation: {prefix!r}")
    spec = ACCELERATOR_SPECS[prefix]
    # v2/v3/v5p accelerator types historically count TensorCores, not chips
    # (v5p-8 = 4 chips × 2 cores); v4 types count chips directly in the
    # "v4-8" = 4 chips sense as well.  Normalise to chips.
    chips = count // spec.cores_per_chip if spec.cores_per_chip > 1 else count
    return spec, max(chips, 1)


def read_tpu_env(path: str = constants.TPU_ENV_FILE) -> Dict[str, str]:
    """Parse the host tpu-env metadata file.

    Format is one ``KEY: 'value'`` or ``KEY=value`` pair per line (the GCE
    metadata attribute uses the former; some runtimes write plain env style).
    Unknown lines are ignored.  Returns {} if the file is absent — discovery
    then falls back to pure sysfs probing.
    """
    env: Dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
    except OSError:
        return env
    for line in raw.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # Split on whichever separator appears first: values may themselves
        # contain the other character (e.g. TPU_PARTITION_MODE_OVERRIDES=2:core).
        ci, ei = line.find(":"), line.find("=")
        if ci == -1 and ei == -1:
            continue
        sep = ":" if (ei == -1 or (ci != -1 and ci < ei)) else "="
        key, _, val = line.partition(sep)
        env[key.strip()] = val.strip().strip("'\"")
    return env


def _parse_bounds(s: str) -> Optional[Tuple[int, int, int]]:
    """Parse "x,y,z" bounds; None on malformed input (callers fall back to
    derived bounds rather than failing discovery on bad metadata)."""
    try:
        parts = [int(p) for p in s.split(",")]
    except ValueError:
        return None
    if not parts or any(p <= 0 for p in parts):
        return None
    while len(parts) < 3:
        parts.append(1)
    return tuple(parts[:3])  # type: ignore[return-value]


@dataclass
class IciTopology:
    """The host's view of the ICI mesh it belongs to.

    ``chips_per_host_bounds`` is the local chip grid (e.g. (2,4,1) for a v5e
    host with 8 chips); ``host_bounds`` the grid of hosts in the slice;
    ``worker_id`` this host's index.  Chip grid coordinates are assigned
    x-fastest (matching the TPU runtime's TPU_VISIBLE_CHIPS ordering).
    """

    accelerator_type: str = ""
    spec: Optional[AcceleratorSpec] = None
    chips_per_host_bounds: Tuple[int, int, int] = (0, 0, 0)
    host_bounds: Tuple[int, int, int] = (1, 1, 1)
    worker_id: int = 0
    wrap: Tuple[bool, bool, bool] = (False, False, False)
    raw_env: Dict[str, str] = field(default_factory=dict)

    @property
    def local_chip_count(self) -> int:
        x, y, z = self.chips_per_host_bounds
        return x * y * z

    @property
    def num_workers(self) -> int:
        x, y, z = self.host_bounds
        return x * y * z

    @property
    def topology_str(self) -> str:
        """Slice-global topology label, e.g. ``2x4`` or ``4x4x4``."""
        dims = [c * h for c, h in zip(self.chips_per_host_bounds, self.host_bounds)]
        if dims[2] == 1 and not (self.spec and self.spec.torus_3d):
            dims = dims[:2]
        return "x".join(str(d) for d in dims)

    def chip_coords(self, index: int) -> Tuple[int, int, int]:
        """Local grid coordinates of a chip by accel index (x-fastest)."""
        x, y, _z = self.chips_per_host_bounds
        if x <= 0 or y <= 0:
            return (index, 0, 0)
        return (index % x, (index // x) % y, index // (x * y))

    def global_chip_coords(self, index: int) -> Tuple[int, int, int]:
        """Slice-global coordinates of a local chip (host offset + local)."""
        hx, hy, _hz = self.host_bounds
        wx = self.worker_id % hx if hx > 0 else 0
        wy = (self.worker_id // hx) % hy if hx > 0 and hy > 0 else 0
        wz = self.worker_id // (hx * hy) if hx > 0 and hy > 0 else 0
        cx, cy, cz = self.chip_coords(index)
        bx, by, bz = self.chips_per_host_bounds
        return (wx * bx + cx, wy * by + cy, wz * bz + cz)

    def coord_distance(
        self, a: Tuple[int, int, int], b: Tuple[int, int, int]
    ) -> int:
        """Torus-aware manhattan ICI hop count between two grid coordinates.
        The single source of truth for ICI distance (the allocator's weight
        model and the labeller both call this)."""
        total_dims = [c * h for c, h in zip(self.chips_per_host_bounds, self.host_bounds)]
        dist = 0
        for axis in range(3):
            d = abs(a[axis] - b[axis])
            if self.wrap[axis] and total_dims[axis] > 0:
                d = min(d, total_dims[axis] - d)
            dist += d
        return dist

    def ici_distance(self, a: int, b: int) -> int:
        """ICI hop count between two local chips by accel index."""
        return self.coord_distance(
            self.global_chip_coords(a), self.global_chip_coords(b)
        )


def topology_from_env(
    env: Dict[str, str], fallback_chip_count: int = 0, pci_device_id: str = ""
) -> IciTopology:
    """Build an IciTopology from tpu-env metadata, with sysfs fallbacks.

    Recognised keys (GCE metadata spelling first, plain-env spelling second):
    ACCELERATOR_TYPE, TPU_ACCELERATOR_TYPE; CHIPS_PER_HOST_BOUNDS,
    TPU_CHIPS_PER_HOST_BOUNDS; HOST_BOUNDS, TPU_HOST_BOUNDS; WORKER_ID,
    TPU_WORKER_ID; WRAP, TPU_WRAP.
    """

    def get(*names: str) -> str:
        for n in names:
            if n in env:
                return env[n]
        return ""

    topo = IciTopology(raw_env=dict(env))

    accel_type = get("ACCELERATOR_TYPE", constants.ENV_TPU_ACCELERATOR_TYPE)
    spec: Optional[AcceleratorSpec] = None
    slice_chips = 0
    if accel_type:
        try:
            spec, slice_chips = parse_accelerator_type(accel_type)
        except ValueError:
            spec = None
    if spec is None and pci_device_id in PCI_DEVICE_TO_PREFIX:
        spec = ACCELERATOR_SPECS[PCI_DEVICE_TO_PREFIX[pci_device_id]]
    topo.accelerator_type = accel_type
    topo.spec = spec

    bounds = _parse_bounds(
        get("CHIPS_PER_HOST_BOUNDS", constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS)
    )
    if bounds is not None:
        topo.chips_per_host_bounds = bounds
    elif spec is not None and fallback_chip_count in (0, _volume(spec.default_chips_per_host)):
        topo.chips_per_host_bounds = spec.default_chips_per_host
    elif fallback_chip_count > 0:
        topo.chips_per_host_bounds = _linear_bounds(fallback_chip_count)

    host_bounds = _parse_bounds(
        get("HOST_BOUNDS", constants.ENV_TPU_PROCESS_BOUNDS, "TPU_HOST_BOUNDS")
    )
    if host_bounds is not None:
        topo.host_bounds = host_bounds
    elif spec is not None and slice_chips and topo.local_chip_count:
        # Derive host grid from slice size when only ACCELERATOR_TYPE is given.
        hosts = max(1, slice_chips // topo.local_chip_count)
        topo.host_bounds = _linear_bounds(hosts)

    wid = get("WORKER_ID", constants.ENV_TPU_WORKER_ID, "AGENT_WORKER_NUMBER")
    if wid:
        try:
            topo.worker_id = int(wid)
        except ValueError:
            pass

    wrap = get("WRAP", "TPU_WRAP")
    if wrap:
        vals = [v.strip().lower() in ("1", "true", "t") for v in wrap.split(",")]
        while len(vals) < 3:
            vals.append(False)
        topo.wrap = tuple(vals[:3])  # type: ignore[assignment]
    elif spec is not None and spec.torus_3d:
        # Full v4/v5p pods wrap each axis; conservatively only claim wrap when
        # an axis spans >= 4 chips (matches TPU wraparound availability).
        total = [c * h for c, h in zip(topo.chips_per_host_bounds, topo.host_bounds)]
        topo.wrap = tuple(t >= 4 for t in total)  # type: ignore[assignment]

    return topo


def derive_worker_identity(
    topo: Optional[IciTopology],
    full_host: bool,
    slice_rank: Optional[int] = None,
    slice_workers: int = 0,
) -> Tuple[int, int]:
    """Single source of the (worker_id, num_workers) pair Allocate injects.

    Both Allocate paths route through here instead of hardcoding worker
    "0" inline: a sub-host grant is a standalone single-process slice
    (0 of 1) whatever the host metadata says; a full-host grant prefers
    the rendezvous-assigned rank when slice coordination agreed on one
    (the per-host tpu-env WORKER_ID is a static guess that desyncs the
    moment pods reschedule), falling back to the metadata view.
    """
    if not full_host or topo is None:
        return 0, 1
    if slice_rank is not None and slice_workers > 0:
        return slice_rank, slice_workers
    return topo.worker_id, topo.num_workers


def _volume(b: Tuple[int, int, int]) -> int:
    return b[0] * b[1] * b[2]


def _linear_bounds(n: int) -> Tuple[int, int, int]:
    """Factor n into a roughly-square 2D grid (x-major)."""
    best = (n, 1, 1)
    for x in range(1, n + 1):
        if n % x == 0:
            y = n // x
            if abs(x - y) <= abs(best[0] - best[1]) and x <= y:
                best = (x, y, 1)
    return best


def partition_modes_from_env(env: Dict[str, str], chip_count: int) -> List[str]:
    """Per-chip partition granularity: "chip" (whole chip) or "core"
    (per-TensorCore sub-device; only meaningful on 2-core generations).

    The TPU analog of the per-GPU compute/memory partition styles the
    reference reads from sysfs (amdgpu.go:464-495).  Global default from
    TPU_PARTITION_MODE, per-chip overrides from TPU_PARTITION_MODE_OVERRIDES
    (e.g. "4:core,5:core"), letting fixtures model heterogeneous hosts.
    """
    default = env.get("TPU_PARTITION_MODE", "chip").strip().lower()
    if default not in ("chip", "core"):
        default = "chip"
    modes = [default] * chip_count
    overrides = env.get("TPU_PARTITION_MODE_OVERRIDES", "")
    for item in overrides.split(","):
        item = item.strip()
        if not item or ":" not in item:
            continue
        idx_s, _, mode = item.partition(":")
        try:
            idx = int(idx_s)
        except ValueError:
            continue
        if 0 <= idx < chip_count and mode in ("chip", "core"):
            modes[idx] = mode
    return modes
