"""Shared sysfs parsing helpers used by discovery and vfio scanning."""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)


def _native():
    """The tpuprobe shim, or None when unbuildable (cached after first
    attempt; import cost includes a one-time g++ build)."""
    global _NATIVE
    if _NATIVE is False:
        try:
            from tpu_k8s_device_plugin.hostinfo import tpuprobe
            _NATIVE = tpuprobe
        except Exception as e:
            # expected on hosts without a toolchain: the pure-python
            # fallback below IS the handling, but the reason must not
            # vanish (tpulint R2)
            log.debug("native tpuprobe shim unavailable (%s); using "
                      "portable sysfs parsing", e)
            _NATIVE = None
    return _NATIVE


_NATIVE = False


def read_file(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        return ""


def read_int(path: str, default: int = 0) -> int:
    s = read_file(path)
    try:
        return int(s, 0)
    except ValueError:
        return default


def numa_node(dev_dir: str) -> int:
    """NUMA node of a PCI device dir, clamped to >= 0 (-1 means unknown).
    Prefers the native shim (≈ the reference routing NUMA through hwloc
    cgo, internal/pkg/hwloc/hwloc.go:69-97) with a pure-Python fallback."""
    native = _native()
    if native is not None:
        rc = native.numa_node(dev_dir)
        if rc >= 0:
            return rc
    return max(read_int(os.path.join(dev_dir, "numa_node"), 0), 0)


def iommu_group(dev_dir: str) -> str:
    """IOMMU group number of a PCI device dir, "" when absent."""
    link = os.path.join(dev_dir, "iommu_group")
    if not os.path.exists(link):
        return ""
    return os.path.basename(os.path.realpath(link))


def driver_name(dev_dir: str) -> str:
    """Bound driver of a PCI device dir, "" when unbound."""
    link = os.path.join(dev_dir, "driver")
    if not os.path.exists(link):
        return ""
    return os.path.basename(os.path.realpath(link))
