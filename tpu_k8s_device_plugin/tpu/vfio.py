"""VFIO discovery for VM-passthrough TPU hosts.

TPU-native analog of the reference's SR-IOV VF and PF scanners
(/root/reference/internal/pkg/amdgpu/amdgpu_sriov.go:323-402 and
amdgpu_pf.go:244-305): scan /sys/bus/pci/devices for Google-vendor
functions, resolve driver binding and IOMMU groups, and key devices by
IOMMU group (the unit VFIO exposes to VMs).
"""

from __future__ import annotations

import glob
import logging
import os
from dataclasses import dataclass
from typing import Dict

from tpu_k8s_device_plugin.types import constants
from . import sysfs

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class VfInfo:
    """One virtual function exposed for VM passthrough."""

    pci_address: str      # VF PCI address (DBDF)
    pf_pci_address: str   # parent physical function
    iommu_group: str      # device id reported to kubelet
    numa_node: int = 0


@dataclass(frozen=True)
class PfInfo:
    """One physical function bound to vfio-pci for whole-chip passthrough."""

    pci_address: str
    iommu_group: str
    numa_node: int = 0


def get_vf_mapping(sysfs_root: str = "/sys") -> Dict[str, VfInfo]:
    """IOMMU group → VfInfo for every VF of a TPU PF bound to the tpu-vf
    host driver (≈ GetVFMapping, amdgpu_sriov.go:323-402)."""
    out: Dict[str, VfInfo] = {}
    pci_dir = os.path.join(sysfs_root, "bus", "pci", "devices")
    for pf_dir in sorted(glob.glob(os.path.join(pci_dir, "*"))):
        if sysfs.read_file(os.path.join(pf_dir, "vendor")) != constants.GOOGLE_VENDOR_ID:
            continue
        if sysfs.driver_name(pf_dir) != constants.TPU_VF_DRIVER_NAME:
            continue
        pf_addr = os.path.basename(os.path.realpath(pf_dir))
        for vf_link in sorted(glob.glob(os.path.join(pf_dir, "virtfn*"))):
            vf_dir = os.path.realpath(vf_link)
            vf_addr = os.path.basename(vf_dir)
            group = sysfs.iommu_group(vf_dir)
            if not group:
                log.warning("VF %s has no IOMMU group; skipping", vf_addr)
                continue
            out[group] = VfInfo(
                pci_address=vf_addr,
                pf_pci_address=pf_addr,
                iommu_group=group,
                numa_node=sysfs.numa_node(vf_dir),
            )
    return out


def get_pf_mapping(sysfs_root: str = "/sys") -> Dict[str, PfInfo]:
    """IOMMU group → PfInfo for every TPU PF bound to vfio-pci
    (≈ GetPFMapping, amdgpu_pf.go:244-305)."""
    out: Dict[str, PfInfo] = {}
    pci_dir = os.path.join(sysfs_root, "bus", "pci", "devices")
    for dev_dir in sorted(glob.glob(os.path.join(pci_dir, "*"))):
        if sysfs.read_file(os.path.join(dev_dir, "vendor")) != constants.GOOGLE_VENDOR_ID:
            continue
        if sysfs.driver_name(dev_dir) != constants.VFIO_DRIVER_NAME:
            continue
        addr = os.path.basename(os.path.realpath(dev_dir))
        group = sysfs.iommu_group(dev_dir)
        if not group:
            log.warning("PF %s has no IOMMU group; skipping", addr)
            continue
        out[group] = PfInfo(
            pci_address=addr, iommu_group=group, numa_node=sysfs.numa_node(dev_dir)
        )
    return out


def get_tpu_vf_module_versions(sysfs_root: str = "/sys") -> Dict[str, str]:
    """tpu-vf host driver version info (≈ GetGIMVersions,
    amdgpu_sriov.go:404-422)."""
    out: Dict[str, str] = {}
    base = os.path.join(sysfs_root, "module",
                        constants.TPU_VF_DRIVER_NAME.replace("-", "_"))
    ver = sysfs.read_file(os.path.join(base, "version"))
    src = sysfs.read_file(os.path.join(base, "srcversion"))
    if ver:
        out["version"] = ver
    if src:
        out["srcversion"] = src
    return out
