"""Label generators: one small function per label key.

Mirrors the reference's generator-map design
(/root/reference/cmd/k8s-node-labeller/main.go:123-385) with TPU content:
where AMD reads libdrm ioctls and KFD topology for family/vram/cu-count,
these read the accel sysfs tree and tpu-env metadata already parsed by the
discovery layer.  Every generator takes the same precomputed context so a
reconcile is pure in-memory work after one discovery pass.
"""

from __future__ import annotations

import logging
import re
import socket
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpu_k8s_device_plugin.slice import Membership, load_membership
from tpu_k8s_device_plugin.tpu import discovery, vfio
from tpu_k8s_device_plugin.tpu.discovery import TpuDevice
from tpu_k8s_device_plugin.tpu.topology import IciTopology
from tpu_k8s_device_plugin.types import constants

# k8s label value rules: <= 63 chars, alphanumeric ends, [-A-Za-z0-9_.] middle.
MAX_LABEL_VALUE_LEN = 63
_LABEL_VALUE_RE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")


def is_valid_label_value(val: str) -> bool:
    return len(val) <= MAX_LABEL_VALUE_LEN and bool(_LABEL_VALUE_RE.match(val))

log = logging.getLogger(__name__)


@dataclass
class LabelContext:
    """Inputs every generator works from (one discovery pass per reconcile)."""

    driver_type: str
    chips: Dict[str, TpuDevice] = field(default_factory=dict)
    topology: Optional[IciTopology] = None
    sysfs_root: str = "/sys"
    # formed multi-host slice membership, from the crash-safe state file
    # the plugin's slice client maintains (absent on single-host nodes)
    slice_membership: Optional[Membership] = None
    hostname: str = ""

    @classmethod
    def collect(
        cls,
        driver_type: str = constants.CONTAINER,
        sysfs_root: str = "/sys",
        dev_root: str = "/dev",
        tpu_env_path: str = constants.TPU_ENV_FILE,
        slice_state_path: str = constants.SLICE_STATE_FILE,
    ) -> "LabelContext":
        chips, topo = discovery.get_tpu_chips(sysfs_root, dev_root, tpu_env_path)
        return cls(
            driver_type=driver_type,
            chips=chips,
            topology=topo,
            sysfs_root=sysfs_root,
            slice_membership=load_membership(slice_state_path),
            hostname=socket.gethostname(),
        )


def _mode(ctx: LabelContext) -> str:
    return ctx.driver_type


def _accelerator_type(ctx: LabelContext) -> str:
    return ctx.topology.accelerator_type if ctx.topology else ""


def _topology(ctx: LabelContext) -> str:
    return ctx.topology.topology_str if ctx.topology else ""


def _chips_per_host(ctx: LabelContext) -> str:
    return str(len(ctx.chips)) if ctx.chips else ""


def _cores_per_chip(ctx: LabelContext) -> str:
    spec = ctx.topology.spec if ctx.topology else None
    return str(spec.cores_per_chip) if spec else ""


def _worker_id(ctx: LabelContext) -> str:
    return str(ctx.topology.worker_id) if ctx.topology else ""


def _num_workers(ctx: LabelContext) -> str:
    return str(ctx.topology.num_workers) if ctx.topology else ""


def _firmware(ctx: LabelContext) -> str:
    for chip in sorted(ctx.chips.values(), key=lambda c: c.id):
        if chip.accel_index >= 0:
            fw = discovery.get_firmware_version(ctx.sysfs_root, chip.accel_index)
        else:
            fw = ""
        if fw:
            return fw
    return ""


def _driver_version(ctx: LabelContext) -> str:
    if ctx.driver_type == constants.VF_PASSTHROUGH:
        vers = vfio.get_tpu_vf_module_versions(ctx.sysfs_root)
        return vers.get("version", "")
    return discovery.get_driver_versions(ctx.sysfs_root).get("driver-version", "")


def _device_id(ctx: LabelContext) -> str:
    # "_" separator: "," is not legal in a k8s label value, and one bad
    # value would get the whole merge patch rejected.  A heterogeneous
    # host with many distinct ids could also blow the 63-char value limit
    # (same whole-patch rejection), so cap the join and summarise the rest.
    ids = sorted({c.device_id for c in ctx.chips.values() if c.device_id})
    if len(ids) == 1:
        return ids[0]
    joined = "_".join(ids)
    if len(joined) <= MAX_LABEL_VALUE_LEN:
        return joined
    kept: List[str] = []
    for i in ids:
        tail = f"_and-{len(ids) - len(kept)}-more"
        if len("_".join(kept + [i])) + len(tail) > MAX_LABEL_VALUE_LEN:
            break
        kept.append(i)
    if not kept:
        # even the first id + summary tail won't fit: a bare count is
        # still a valid label value ("_and-N-more" alone would not be)
        return f"{len(ids)}-device-ids"
    return "_".join(kept) + f"_and-{len(ids) - len(kept)}-more"


def _product_name(ctx: LabelContext) -> str:
    spec = ctx.topology.spec if ctx.topology else None
    # label values cannot contain spaces or parens; slugify
    if spec is None:
        return ""
    return spec.product_name.replace(" ", "-").replace("(", "").replace(")", "")


def _hbm(ctx: LabelContext) -> str:
    spec = ctx.topology.spec if ctx.topology else None
    if spec is None:
        return ""
    gib = spec.hbm_bytes_per_chip // (1024 ** 3)
    return f"{gib}Gi"


def _partitioning_supported(ctx: LabelContext) -> str:
    spec = ctx.topology.spec if ctx.topology else None
    if spec is None:
        return ""
    return "true" if spec.cores_per_chip > 1 else "false"


def _core_partition(ctx: LabelContext) -> str:
    if not ctx.chips:
        return ""
    modes = {c.partition_mode for c in ctx.chips.values()}
    return "mixed" if len(modes) > 1 else next(iter(modes))


def _slice_id(ctx: LabelContext) -> str:
    """Rendezvous slice identity — the pod-affinity key that pins a
    multi-host workload's pods onto hosts of the SAME formed slice
    (example/multihost/README.md's 'slice-identity labels')."""
    m = ctx.slice_membership
    return m.slice_id if m is not None else ""


def _slice_rank(ctx: LabelContext) -> str:
    m = ctx.slice_membership
    if m is None:
        return ""
    rank = m.rank_of(ctx.hostname)
    return str(rank) if rank is not None else ""


def _slice_generation(ctx: LabelContext) -> str:
    """Membership generation: bumps whenever the member set changes
    (degraded-mode reshape, evicted member returning) — gang schedulers
    and operators can tell a re-formed slice from the original."""
    m = ctx.slice_membership
    return str(m.generation) if m is not None else ""


def _slice_workers(ctx: LabelContext) -> str:
    """Hosts in the CURRENT generation.  Unlike num-workers (the
    metadata-declared slice size), this shrinks when a reshape evicts a
    member — the real remaining shape schedulers should place against."""
    m = ctx.slice_membership
    return str(m.num_workers) if m is not None else ""


def _slice_degraded(ctx: LabelContext) -> str:
    """'true' while the slice runs below its configured worker count
    (a reshape evicted members), 'false' on a whole slice — the
    anti-affinity key for jobs that must not land on reduced capacity."""
    m = ctx.slice_membership
    if m is None:
        return ""
    return "true" if m.degraded else "false"


# key → generator; keys are the SUPPORTED_LABELS flag names
# (≈ labelGenerators, main.go:123).
LABEL_GENERATORS: Dict[str, Callable[[LabelContext], str]] = {
    "mode": _mode,
    "accelerator-type": _accelerator_type,
    "topology": _topology,
    "chips-per-host": _chips_per_host,
    "cores-per-chip": _cores_per_chip,
    "worker-id": _worker_id,
    "num-workers": _num_workers,
    "firmware": _firmware,
    "driver-version": _driver_version,
    "device-id": _device_id,
    "product-name": _product_name,
    "hbm": _hbm,
    "partitioning-supported": _partitioning_supported,
    "core-partition": _core_partition,
    "slice-id": _slice_id,
    "slice-rank": _slice_rank,
    "slice-generation": _slice_generation,
    "slice-workers": _slice_workers,
    "slice-degraded": _slice_degraded,
}

assert set(LABEL_GENERATORS) == set(constants.SUPPORTED_LABELS)


def generate_labels(
    ctx: LabelContext, enabled: Optional[List[str]] = None
) -> Dict[str, str]:
    """Fully-qualified label map for the enabled generators, under both the
    primary and legacy prefixes (≈ createLabelPrefix + generation loop,
    main.go:85-116, 410-430).  Empty values are dropped — absent data must
    not become an empty label."""
    keys = enabled if enabled is not None else list(LABEL_GENERATORS)
    out: Dict[str, str] = {}
    for key in keys:
        gen = LABEL_GENERATORS.get(key)
        if gen is None:
            log.warning("unknown label %s; skipping", key)
            continue
        try:
            val = gen(ctx)
        except Exception as e:
            log.error("label generator %s failed: %s", key, e)
            continue
        if not val:
            continue
        if not is_valid_label_value(val):
            # one invalid value rejects the ENTIRE merge patch — every
            # other label would stop reconciling with it.  Drop and log.
            log.error(
                "label %s value %r is not a valid k8s label value; dropping",
                key, val,
            )
            continue
        out[f"{constants.LABEL_PREFIX}.{key}"] = val
        out[f"{constants.LABEL_PREFIX_BETA}.{key}"] = val
    return out
