"""Reconcile controller: keep this node's TPU labels in sync.

≈ the reference's controller-runtime Reconcile
(/root/reference/cmd/k8s-node-labeller/controller.go:23-58) plus its
stale-label sweep (main.go:64-83), with two deliberate upgrades flagged in
SURVEY.md §7: labels are recomputed on every reconcile (the reference
computes once at startup, so partition changes need a pod restart), and the
whole delta — removals included — lands in one merge-patch request instead
of a read-modify-update of the full Node object.
"""

from __future__ import annotations

import http.client
import logging
import threading
from typing import Callable, Dict, Optional

from tpu_k8s_device_plugin.types import constants
from .k8s_client import ApiError, NodeClient

log = logging.getLogger(__name__)

_PREFIXES = (f"{constants.LABEL_PREFIX}.", f"{constants.LABEL_PREFIX_BETA}.")


def label_delta(
    current: Dict[str, str], desired: Dict[str, str]
) -> Dict[str, Optional[str]]:
    """Merge-patch delta from a node's current labels to the desired set:
    stale labels under our prefixes → None (delete), changed/new → value."""
    delta: Dict[str, Optional[str]] = {}
    for key in current:
        if key.startswith(_PREFIXES) and key not in desired:
            delta[key] = None
    for key, val in desired.items():
        if current.get(key) != val:
            delta[key] = val
    return delta


class NodeLabelController:
    """Periodic (and watch-triggered) reconciliation of one node's labels."""

    def __init__(
        self,
        client: NodeClient,
        node_name: str,
        compute_labels: Callable[[], Dict[str, str]],
        interval_s: float = 60.0,
    ):
        self.client = client
        self.node_name = node_name
        self.compute_labels = compute_labels
        self.interval = interval_s
        self._stop = threading.Event()
        # resourceVersion to resume the watch from (informer semantics);
        # None forces the next watch to start fresh after a re-list
        self._last_rv: Optional[str] = None

    def reconcile(
        self, desired: Optional[Dict[str, str]] = None
    ) -> Dict[str, Optional[str]]:
        """One pass; returns the applied delta (empty = already in sync).
        *desired* skips recomputation when the caller already has it."""
        node = self.client.get_node(self.node_name)
        meta = node.get("metadata") or {}
        self._last_rv = meta.get("resourceVersion")
        current = meta.get("labels") or {}
        if desired is None:
            desired = self.compute_labels()
        delta = label_delta(current, desired)
        if delta:
            updated = self.client.patch_node_labels(self.node_name, delta)
            # resume the watch from the PATCH response's version: it IS our
            # own update, so starting there also skips the self-induced
            # MODIFIED event a replay from the GET's version would deliver
            rv = (updated.get("metadata") or {}).get("resourceVersion")
            if rv:
                self._last_rv = rv
            log.info(
                "reconciled %s: %d set, %d removed",
                self.node_name,
                sum(1 for v in delta.values() if v is not None),
                sum(1 for v in delta.values() if v is None),
            )
        return delta

    @staticmethod
    def _event_needs_reconcile(event: dict, desired: Dict[str, str]) -> bool:
        """Cheap filter before paying a discovery pass: skip watch events
        whose label state already matches what we last computed.  Weeds out
        the watch's initial replay of the current object, the MODIFIED we
        cause with our own PATCH, and kubelet status heartbeats."""
        if event.get("type") not in ("ADDED", "MODIFIED"):
            return False
        obj = event.get("object") or {}
        current = (obj.get("metadata") or {}).get("labels") or {}
        return bool(label_delta(current, desired))

    def run(self) -> None:
        """Reconcile loop: immediate pass, then watch the node for changes
        with the interval as both watch timeout and error backoff.  The
        watch replaces the reference's controller-runtime Node informer
        (main.go:551-577) — filtered to our own node by field selector."""
        while not self._stop.is_set():
            try:
                desired = self.compute_labels()
                self.reconcile(desired)
            except (ApiError, OSError, http.client.HTTPException) as e:
                log.error("reconcile failed: %s", e)
                self._stop.wait(min(self.interval, 10.0))
                continue
            try:
                for event in self.client.watch_node(
                    self.node_name, timeout_s=int(self.interval),
                    resource_version=self._last_rv,
                ):
                    if self._stop.is_set():
                        return
                    if self._handle_gone(event):
                        break  # clean re-list via the outer loop, no backoff
                    desired = self._process_event(event, desired)
            except ApiError as e:
                if e.status == 410:
                    # history compacted past our resourceVersion: re-list
                    # immediately (informer semantics), not generic backoff
                    log.info("watch expired (410 Gone); re-listing")
                    self._last_rv = None
                    continue
                log.warning("watch failed (%s); falling back to poll", e)
                self._stop.wait(self.interval)
            except (OSError, http.client.HTTPException) as e:
                # HTTPException: a dropped chunked stream mid-read raises
                # IncompleteRead and friends, which are NOT OSErrors — an
                # apiserver restart must not kill the reconcile loop
                log.warning("watch failed (%s); falling back to poll", e)
                self._stop.wait(self.interval)

    def _process_event(
        self, event: dict, desired: Dict[str, str]
    ) -> Dict[str, str]:
        """One non-ERROR watch event: advance the resume point to the
        event's resourceVersion (so a mid-stream reconnect doesn't replay
        it), then reconcile if the labels drifted.  Returns the possibly
        recomputed desired set."""
        rv = (
            (event.get("object") or {}).get("metadata") or {}
        ).get("resourceVersion")
        if rv:
            self._last_rv = rv
        if self._event_needs_reconcile(event, desired):
            # recompute: the divergence may reflect new hardware
            # state, not just someone deleting our labels
            desired = self.compute_labels()
            self.reconcile(desired)
        return desired

    def _handle_gone(self, event: dict) -> bool:
        """True for a 410 Gone ERROR event (etcd compacted past our
        resourceVersion) — the watch must be restarted from a fresh list."""
        if event.get("type") != "ERROR":
            return False
        code = (event.get("object") or {}).get("code")
        if code == 410:
            log.info("watch event 410 Gone; re-listing")
            self._last_rv = None
            return True
        log.warning("watch ERROR event: %s", event)
        return False

    def stop(self) -> None:
        self._stop.set()
