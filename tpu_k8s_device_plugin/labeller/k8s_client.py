"""Minimal in-cluster Kubernetes API client for Node objects.

The reference leans on controller-runtime for its Node updates
(/root/reference/cmd/k8s-node-labeller/main.go:529-577); this build needs
only three verbs against one resource, so a stdlib HTTPS client keeps the
image dependency-free: GET node, PATCH labels (JSON merge patch — a null
value deletes a label, which makes stale-label cleanup a single request),
and a long-poll WATCH for the controller loop.

In-cluster config is the standard service-account mount; every path and the
API base URL are injectable so tests drive it against a local fake.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import urllib.error
import urllib.request
from typing import Dict, Iterator, Optional

from tpu_k8s_device_plugin import resilience
from tpu_k8s_device_plugin.resilience import faults

log = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"API server returned {status}: {body[:200]}")
        self.status = status
        self.body = body


class TransientApiError(ApiError):
    """5xx/429 — the API server's problem, safe to retry.  Subclasses
    ApiError so existing ``except ApiError`` callers see no change."""


# the failures worth retrying a node GET/PATCH over: connection-level
# faults, server-side 5xx/429, and injected faults in chaos runs
_RETRYABLE = (TransientApiError, urllib.error.URLError, TimeoutError,
              ConnectionError, faults.InjectedFault)


class NodeClient:
    """Talks to ``/api/v1/nodes`` with service-account credentials."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token_path: str = os.path.join(SA_DIR, "token"),
        ca_path: str = os.path.join(SA_DIR, "ca.crt"),
        timeout_s: float = 10.0,
        retry: Optional["resilience.RetryPolicy"] = None,
        resilience_metrics: Optional[
            "resilience.ResilienceMetrics"] = None,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        self._token_path = token_path
        self._timeout = timeout_s
        # shared policy: transient API-server faults (connection reset,
        # 5xx, 429) retry with jittered backoff instead of failing the
        # whole reconcile round
        self._retry = retry if retry is not None else \
            resilience.RetryPolicy(max_attempts=3,
                                   initial_backoff_s=0.25,
                                   max_backoff_s=2.0)
        self._res_metrics = resilience_metrics
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https") and os.path.exists(ca_path):
            self._ssl_ctx = ssl.create_default_context(cafile=ca_path)

    # -- plumbing -----------------------------------------------------------

    def _token(self) -> str:
        # re-read per request: projected SA tokens rotate
        try:
            with open(self._token_path, "r", encoding="utf-8") as f:
                return f.read().strip()
        except OSError:
            return ""

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        content_type: str = "application/json",
        timeout: Optional[float] = None,
        retryable: bool = True,
    ):
        """One API-server round trip; *retryable* GET/PATCH calls run
        under the shared RetryPolicy (long-poll WATCH passes False —
        its reconnect loop belongs to the controller)."""
        def _once():
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("k8s.request")
            req = urllib.request.Request(
                self.base_url + path,
                method=method,
                data=json.dumps(body).encode()
                if body is not None else None,
            )
            token = self._token()
            if token:
                req.add_header("Authorization", f"Bearer {token}")
            req.add_header("Accept", "application/json")
            if body is not None:
                req.add_header("Content-Type", content_type)
            try:
                return urllib.request.urlopen(
                    req, timeout=timeout or self._timeout,
                    context=self._ssl_ctx
                )
            except urllib.error.HTTPError as e:
                text = e.read().decode(errors="replace")
                if e.code >= 500 or e.code == 429:
                    raise TransientApiError(e.code, text) from e
                raise ApiError(e.code, text) from e

        if not retryable:
            return _once()
        return self._retry.call(
            _once, op="k8s.request", retry_on=_RETRYABLE,
            metrics=self._res_metrics, logger=log)

    # -- node verbs ---------------------------------------------------------

    def get_node(self, name: str) -> dict:
        with self._request("GET", f"/api/v1/nodes/{name}") as resp:
            return json.load(resp)

    def patch_node_labels(
        self, name: str, labels: Dict[str, Optional[str]]
    ) -> dict:
        """Apply a label delta; a None value removes that label (JSON merge
        patch semantics, RFC 7386)."""
        patch = {"metadata": {"labels": labels}}
        with self._request(
            "PATCH",
            f"/api/v1/nodes/{name}",
            body=patch,
            content_type="application/merge-patch+json",
        ) as resp:
            return json.load(resp)

    def watch_node(
        self, name: str, timeout_s: int = 60,
        resource_version: Optional[str] = None,
    ) -> Iterator[dict]:
        """Yield watch events for one node until the server closes the
        long-poll (bounded by ``timeoutSeconds``).

        With *resource_version* the server only sends events newer than
        that version (informer semantics — no replay of the current
        object on every reconnect).  A too-old version surfaces as HTTP
        410 (ApiError) or an ERROR event with ``object.code == 410``;
        callers must then re-list and restart the watch fresh."""
        path = (
            f"/api/v1/nodes?watch=true"
            f"&fieldSelector=metadata.name%3D{name}"
            f"&timeoutSeconds={timeout_s}"
        )
        if resource_version:
            path += f"&resourceVersion={resource_version}"
        with self._request("GET", path, timeout=timeout_s + 5,
                           retryable=False) as resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    log.warning("unparseable watch line: %r", line[:120])
