"""Node labeller: publishes TPU properties as Kubernetes node labels.

TPU-native analog of cmd/k8s-node-labeller
(/root/reference/cmd/k8s-node-labeller/main.go:507-590, controller.go:23-58):
a generator map computes labels from discovery + topology, a small stdlib
API-server client applies them, and a reconcile controller keeps them
fresh — recomputing on every reconcile rather than once at startup (the
reference computes once, flagged in SURVEY.md §7 "What NOT to copy").
"""

from .generators import LabelContext, generate_labels, LABEL_GENERATORS
from .k8s_client import NodeClient
from .controller import NodeLabelController

__all__ = [
    "LabelContext",
    "LABEL_GENERATORS",
    "NodeClient",
    "NodeLabelController",
    "generate_labels",
]
